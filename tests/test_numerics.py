"""Model-numerics plane: in-jit tensor stats, NaN provenance, and
gradient-drift detection (framework/numerics.py).

Acceptance (deterministic, CPU-only): with chaos NaN-poisoning ONE
layer's gradients at step K (``train.step_grads`` + ``payload_index``),
the ``train.nan_skip`` flight event names that leaf as
``first_bad_leaf`` and the run recovers; the grad-norm detector flags
an injected 10× spike within 3 steps on a clean baseline; arming the
plane leaves the loss trajectory bitwise unchanged and the DISARMED
step's signature (and compiled executable) identical to the seed's —
no extra outputs, no recompile.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework import chaos, health, monitor, numerics
from paddle_tpu.framework.flags import get_flags, set_flags
from paddle_tpu.framework.observability import flight, validate_prometheus
from paddle_tpu.framework.resilient import ResilientTrainStep
from paddle_tpu.jit import TrainStep


@pytest.fixture(autouse=True)
def _fresh_plane():
    saved = get_flags(["numerics", "numerics_sample_every"])
    chaos.reset(0)
    health.reset()
    numerics.reset()
    flight.clear()
    for s in ("numerics_nonfinite_steps_total",
              "numerics_observe_errors_total",
              "numerics_grad_norm", "numerics_param_norm",
              "numerics_update_ratio", "numerics_max_abs_grad",
              "numerics_grad_norm[weight]", "numerics_nonfinite[w]",
              "numerics_grad_norm[aux_w]",
              "health_anomalies_total", "train_nan_skips_total",
              "jit_compiles_total", "jit_cache_hits_total",
              "health_anomaly_grad_norm_total",
              "health_anomaly_update_ratio_total",
              "amp_scale_collapses_total"):
        monitor.reset_stat(s)
    yield
    set_flags(saved)
    chaos.reset(0)
    health.reset()
    numerics.reset()


def _mse_parts():
    rng = np.random.default_rng(7)
    x = paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    return x, y


def _linear_step(seed=0, **kw):
    paddle.seed(seed)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    return TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt,
                     **kw)


class TwoBranch(nn.Layer):
    """A dense head plus an INDEPENDENT ``aux_w * z`` branch: poisoning
    ``z`` NaNs exactly ``aux_w``'s gradient (the additive branch
    contributes a zero cotangent to the dense leaves), so per-leaf
    provenance has a unique right answer."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 4)
        self.aux_w = self.create_parameter(
            [4],
            default_initializer=paddle.nn.initializer.Constant(0.1))

    def forward(self, x, z):
        return self.fc(x), (self.aux_w * z).sum()


def _two_branch_step(seed=0):
    paddle.seed(seed)
    net = TwoBranch()
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())

    def loss_fn(m, x, z, y):
        out, aux = m(x, z)
        return ((out - y) ** 2).mean() + 1e-3 * aux

    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32))
    z = paddle.to_tensor(rng.standard_normal((4,)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    return TrainStep(net, loss_fn, opt), (x, z, y)


# ---------------------------------------------------------------------------
# arming is free: bitwise trajectory parity + no recompile when off
# ---------------------------------------------------------------------------

class TestArmingIsFree:
    def test_loss_trajectory_bitwise_unchanged(self):
        x, y = _mse_parts()
        step_off = _linear_step(seed=0)
        losses_off = [float(step_off(x, y)) for _ in range(8)]
        set_flags({"numerics": True})
        step_on = _linear_step(seed=0)
        losses_on = [float(step_on(x, y)) for _ in range(8)]
        # the aux is pure extra reductions over values the step already
        # computes: bit-for-bit identical losses, not just close
        assert [np.float32(a).tobytes() for a in losses_off] == \
               [np.float32(a).tobytes() for a in losses_on]
        p_off = {n: np.asarray(p._data)
                 for n, p in step_off.model.named_parameters()}
        p_on = {n: np.asarray(p._data)
                for n, p in step_on.model.named_parameters()}
        for n in p_off:
            assert p_off[n].tobytes() == p_on[n].tobytes(), n

    def test_disarmed_signature_identical_no_recompile(self):
        """The e2e acceptance's compile half: disarmed calls reuse ONE
        cache entry across an arm/disarm cycle — the disarmed signature
        (hence traced jaxpr) never changes, and arming adds exactly one
        new entry instead of churning the cache."""
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        step(x, y)
        step(x, y)
        assert monitor.get_stat("jit_compiles_total") == 1
        assert len(step._cache) == 1
        set_flags({"numerics": True})
        step(x, y)                      # armed: one new entry
        assert monitor.get_stat("jit_compiles_total") == 2
        assert len(step._cache) == 2
        assert hasattr(step, "last_numerics")
        set_flags({"numerics": False})
        hits = monitor.get_stat("jit_cache_hits_total")
        step(x, y)                      # disarmed again: cache HIT
        assert monitor.get_stat("jit_compiles_total") == 2
        assert monitor.get_stat("jit_cache_hits_total") == hits + 1
        assert len(step._cache) == 2

    def test_disarmed_step_has_no_aux_outputs(self):
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        step(x, y)
        assert not hasattr(step, "last_numerics")
        assert monitor.get_stat("numerics_nonfinite_steps_total") == 0


# ---------------------------------------------------------------------------
# the record: norms, ratios, NaN propagation
# ---------------------------------------------------------------------------

class TestRecord:
    def test_global_and_per_leaf_values(self):
        set_flags({"numerics": True})
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        step(x, y)
        rec = step.last_numerics
        per = rec.per_leaf()
        assert set(per) == {"weight", "bias"}
        # global = sqrt of summed per-leaf squares
        g = math.sqrt(sum(d["grad_norm"] ** 2 for d in per.values()))
        assert rec.grad_norm == pytest.approx(g, rel=1e-6)
        assert rec.update_ratio > 0.0
        assert rec.max_abs_grad >= max(d["max_abs_grad"]
                                       for d in per.values()) - 1e-9
        assert rec.finite() and rec.first_bad_leaf() is None

    def test_nan_propagates_not_clamped(self):
        """max(0.0, nan) is 0.0 in Python — a NaN sum-of-squares must
        surface as a NaN norm, not a silent zero that would feed the
        drift detector a fake healthy sample."""
        aux = {"grad_sq": np.array([np.nan], np.float32),
               "param_sq": np.array([1.0], np.float32),
               "update_sq": np.array([np.nan], np.float32),
               "grad_maxabs": np.array([np.nan], np.float32),
               "grad_nonfinite": np.array([1], np.int32),
               "param_nonfinite": np.array([0], np.int32),
               "loss_nonfinite": np.int32(0)}
        rec = numerics.NumericsRecord(["w"], aux)
        assert math.isnan(rec.grad_norm)
        assert math.isnan(rec.update_ratio)
        assert not rec.finite()
        assert rec.first_bad_leaf() == "w"

    def test_publish_keeps_nonfinite_out_of_histograms(self):
        aux = {"grad_sq": np.array([np.nan], np.float32),
               "param_sq": np.array([1.0], np.float32),
               "update_sq": np.array([0.0], np.float32),
               "grad_maxabs": np.array([np.nan], np.float32),
               "grad_nonfinite": np.array([1], np.int32),
               "param_nonfinite": np.array([0], np.int32),
               "loss_nonfinite": np.int32(1)}
        before = monitor.get_histogram("grad_norm").count
        numerics.publish(numerics.NumericsRecord(["w"], aux))
        assert monitor.get_histogram("grad_norm").count == before
        assert monitor.get_stat("numerics_nonfinite_steps_total") == 1
        # the per-leaf attribution refreshes on EVERY non-finite step
        assert monitor.get_stat("numerics_nonfinite[w]") == 1


# ---------------------------------------------------------------------------
# NaN provenance: one poisoned layer -> the right leaf, end to end
# ---------------------------------------------------------------------------

class TestProvenance:
    def test_poisoned_leaf_named_in_flight_event(self):
        """The e2e acceptance: a NaN seeded into ONE layer's gradients
        at step K is (a) attributed to that leaf in train.nan_skip, and
        (b) flagged by the grad-norm drift detector AT step K."""
        set_flags({"numerics": True})
        numerics.watch_defaults()
        step, (x, z, y) = _two_branch_step()
        resilient = ResilientTrainStep(step)
        # poison ONLY the z input (payload index 1): the NaN reaches
        # exactly aux_w's gradient
        chaos.arm("train.step_grads", mode="nan", nth=4, n_times=1,
                  payload_index=1)
        losses, bad_rec, fired_at = [], None, None
        for k in range(7):
            losses.append(float(resilient(x, z, y)))
            if resilient.last_step_skipped:
                bad_rec = step.last_numerics
            if fired_at is None and monitor.get_stat(
                    "health_anomaly_grad_norm_total") >= 1:
                fired_at = k
        assert resilient.skipped_steps == 1
        assert resilient.last_bad_leaf == "aux_w"
        assert np.isfinite(losses[-1])
        ev = flight.recent(20, kind="train.nan_skip")
        assert len(ev) == 1
        assert ev[0]["attrs"]["first_bad_leaf"] == "aux_w"
        # the poisoned step's record: aux_w non-finite, dense leaves
        # clean — the attribution is unique, not first-in-traversal
        assert bad_rec is not None and bad_rec.bad_leaves() == ["aux_w"]
        # the detector fired AT the poisoned step (index 3), and the
        # NaN never taught the baseline anything (no later anomalies)
        assert fired_at == 3
        assert monitor.get_stat("health_anomaly_grad_norm_total") == 1

    def test_whole_batch_poison_still_recovers_and_attributes(self):
        set_flags({"numerics": True})
        step, (x, z, y) = _two_branch_step()
        resilient = ResilientTrainStep(step)
        chaos.arm("train.step_grads", mode="nan", nth=3, n_times=1)
        losses = [float(resilient(x, z, y)) for _ in range(6)]
        assert resilient.skipped_steps == 1
        assert np.isfinite(losses[-1])
        ev = flight.recent(20, kind="train.nan_skip")
        assert ev[0]["attrs"]["first_bad_leaf"] is not None

    def test_armed_rollback_matches_host_path(self):
        """The in-jit finite check is a drop-in for the host sweep:
        identical skip/restore behavior and final state on the same
        poisoned run (the satellite's no-behavior-change contract)."""
        def run(armed):
            chaos.reset(0)
            set_flags({"numerics": armed})
            step, (x, z, y) = _two_branch_step(seed=1)
            res = ResilientTrainStep(step)
            chaos.arm("train.step_grads", mode="nan", nth=3, n_times=1,
                      payload_index=1)
            losses = []
            for _ in range(6):
                losses.append(float(res(x, z, y)))
            return ([l for l in losses if np.isfinite(l)],  # noqa: E741
                    res.skipped_steps, res.rollbacks,
                    {n: np.asarray(p._data).tobytes()
                     for n, p in step.model.named_parameters()})
        l_off, s_off, r_off, p_off = run(False)
        l_on, s_on, r_on, p_on = run(True)
        assert s_off == s_on == 1 and r_off == r_on
        assert l_off == l_on
        assert p_off == p_on

    def test_host_fallback_when_disarmed(self):
        step, (x, z, y) = _two_branch_step()
        resilient = ResilientTrainStep(step)
        chaos.arm("train.step_grads", mode="nan", nth=2, n_times=1)
        for _ in range(4):
            resilient(x, z, y)
        assert resilient.skipped_steps == 1
        assert resilient.last_bad_leaf is None     # no aux disarmed
        ev = flight.recent(20, kind="train.nan_skip")
        assert ev[0]["attrs"]["first_bad_leaf"] is None


# ---------------------------------------------------------------------------
# drift detection: a 10x grad spike trips within 3 steps
# ---------------------------------------------------------------------------

class TestDriftDetection:
    def test_grad_spike_flagged_within_3_steps(self):
        set_flags({"numerics": True})
        numerics.watch_defaults(warmup=8)
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        for _ in range(12):              # clean baseline past warmup
            step(x, y)
        assert monitor.get_stat("health_anomaly_grad_norm_total") == 0
        base = step.last_numerics.grad_norm
        x10 = paddle.to_tensor(np.asarray(x.numpy()) * 10.0)
        spike_step = None
        for k in range(3):
            step(x10, y)
            if monitor.get_stat("health_anomaly_grad_norm_total") >= 1:
                spike_step = k
                break
        assert spike_step == 0, "10x spike not flagged within 3 steps"
        assert step.last_numerics.grad_norm > 5 * base
        ev = flight.recent(20, kind="health.anomaly")
        assert any(e["attrs"]["signal"] == "grad_norm" for e in ev)

    def test_detector_nonfinite_rule(self):
        """A non-finite observation is an anomaly by definition: z=inf,
        flagged even during warmup, and never folded into the EWMA or
        the baseline window (one NaN must not poison either)."""
        d = health.Detector("t", warmup=8)
        a = d.update(float("nan"))
        assert a is not None and a.z == float("inf")
        assert d.ewma is None                 # EWMA untouched
        for _ in range(8):                    # warmup continues cleanly
            assert d.update(1.0) is None
        assert d.update(1.0) is None          # scored, clean
        assert d.ewma == pytest.approx(1.0)
        assert d.update(float("inf")) is not None
        assert d.update(1.0) is None          # baseline survived

    def test_isolated_warmup_nans_do_not_ratchet_rebaseline(self):
        """A clean warmup sample breaks the anomaly streak: isolated
        NaNs scattered through warmup must not accumulate to
        max_consecutive and wipe the forming baseline."""
        d = health.Detector("t", warmup=16, max_consecutive=4)
        for _ in range(4):                    # 4 isolated NaN episodes
            assert d.update(float("nan")) is not None
            for _ in range(3):
                d.update(1.0)
        assert d.rebaselines == 0
        assert d.consecutive == 0

    def test_watch_defaults_idempotent_and_in_default_signals(self):
        dets = numerics.watch_defaults()
        assert set(dets) == set(numerics.DRIFT_SIGNALS)
        # one source of truth: the kwargs live in health.DEFAULT_SIGNALS
        for s in numerics.DRIFT_SIGNALS:
            assert s in health.DEFAULT_SIGNALS
        again = numerics.watch_defaults(warmup=99)
        assert again["grad_norm"] is dets["grad_norm"]   # not re-armed


# ---------------------------------------------------------------------------
# the watcher never crashes the watched (numerics.observe chaos point)
# ---------------------------------------------------------------------------

class TestChaosContract:
    def test_injected_publish_fault_swallowed(self):
        set_flags({"numerics": True})
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        with chaos.inject("numerics.observe", mode="error", every=1):
            losses = [float(step(x, y)) for _ in range(4)]
        assert all(np.isfinite(losses))
        assert monitor.get_stat("numerics_observe_errors_total") == 4
        # faulted publishes left no gauges behind
        assert monitor.get_stat("numerics_grad_norm") == 0
        # recovery: the next publish lands normally
        step(x, y)
        assert monitor.get_stat("numerics_grad_norm") > 0

    def test_latency_fault_absorbed(self):
        set_flags({"numerics": True})
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        with chaos.inject("numerics.observe", mode="latency",
                          latency=0.01, every=2):
            losses = [float(step(x, y)) for _ in range(4)]
        assert all(np.isfinite(losses))
        assert monitor.get_stat("numerics_observe_errors_total") == 0


# ---------------------------------------------------------------------------
# sharded parity: dp=2 sum-of-squares + psum == single-replica norms
# ---------------------------------------------------------------------------

class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(paddle.nn.functional.relu(self.fc1(x)))


def _mlp_loss(m, x, y):
    return ((m(x) - y) ** 2).mean()


class TestShardedParity:
    def test_sharded_train_step_armed_aux(self):
        """ShardedTrainStep (pjit/GSPMD) is the fourth instrumented
        class: the armed out_shardings branch must build, run, and
        stash a sane record."""
        import jax

        from paddle_tpu.parallel import make_mesh, set_mesh
        from paddle_tpu.parallel.sharded import ShardedTrainStep
        set_flags({"numerics": True})
        paddle.seed(2)
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        set_mesh(mesh)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        step = ShardedTrainStep(
            net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt,
            mesh=mesh, sharding_stage=1)
        x, y = _mse_parts()
        for _ in range(2):
            loss = step(x, y)
        assert np.isfinite(float(loss))
        rec = step.last_numerics
        assert set(rec.per_leaf()) == {"weight", "bias"}
        assert rec.grad_norm > 0 and rec.finite()

    def test_global_grad_norm_dp2_matches_single_replica(self):
        import jax

        from paddle_tpu.parallel import make_mesh, set_mesh
        from paddle_tpu.parallel.zero import ShardedUpdateTrainStep
        set_flags({"numerics": True})
        rng = np.random.default_rng(11)
        xb = rng.standard_normal((8, 8)).astype(np.float32)
        yb = rng.standard_normal((8, 4)).astype(np.float32)

        paddle.seed(5)
        m1 = _MLP()
        o1 = paddle.optimizer.SGD(learning_rate=0.05,
                                  parameters=m1.parameters())
        ref = TrainStep(m1, _mlp_loss, o1, donate=False)
        ref(paddle.to_tensor(xb), paddle.to_tensor(yb))
        r_ref = ref.last_numerics

        paddle.seed(5)
        m2 = _MLP()
        o2 = paddle.optimizer.SGD(learning_rate=0.05,
                                  parameters=m2.parameters())
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        set_mesh(mesh)
        z = ShardedUpdateTrainStep(m2, _mlp_loss, o2, mesh=mesh,
                                   wire_dtype="f32", donate=False)
        z(paddle.to_tensor(xb), paddle.to_tensor(yb))
        r_z = z.last_numerics

        assert r_z.grad_norm == pytest.approx(r_ref.grad_norm, rel=1e-5)
        assert r_z.param_norm == pytest.approx(r_ref.param_norm,
                                               rel=1e-5)
        assert r_z.update_ratio == pytest.approx(r_ref.update_ratio,
                                                 rel=1e-4)
        assert r_z.nonfinite_grads == 0 and r_z.first_bad_leaf() is None
        # leaf set matches the shard-spec bookkeeping, per-leaf norms
        # agree with the replicated reference
        per_ref, per_z = r_ref.per_leaf(), r_z.per_leaf()
        assert set(per_ref) == set(per_z)
        for n in per_ref:
            assert per_z[n]["grad_norm"] == pytest.approx(
                per_ref[n]["grad_norm"], rel=1e-5, abs=1e-8), n


# ---------------------------------------------------------------------------
# PSTrainStep: the pulled-row gradient is a first-class numerics leaf
# ---------------------------------------------------------------------------

class TestPSTrainStep:
    def test_embedding_rows_leaf(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu import optimizer
        from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                               PSTrainStep)
        from paddle_tpu.models import WideDeepHost
        set_flags({"numerics": True})
        V, E, fields, dd = 100, 8, 4, 3
        emb = DistributedEmbedding(V, E + 1, optimizer="sgd",
                                   learning_rate=0.05, seed=0)
        model = WideDeepHost(embedding_dim=E, num_fields=fields,
                             dense_dim=dd, hidden=(16,))
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())

        def loss_fn(m, rows, x, y):
            return F.binary_cross_entropy_with_logits(
                m(rows, x), y).mean()

        step = PSTrainStep(model, loss_fn, opt, emb,
                           transfer_dtype="float32")
        rng = np.random.default_rng(0)
        ids = rng.integers(0, V, size=(16, fields)).astype(np.int64)
        x = paddle.to_tensor(rng.standard_normal((16, dd))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 2, (16, 1))
                             .astype(np.float32))
        for _ in range(3):
            step(ids, x, y)
        step.flush()
        rec = step.last_numerics
        per = rec.per_leaf()
        assert "embedding.rows" in per
        assert per["embedding.rows"]["grad_norm"] > 0
        # the sparse update happens host-side on the PS: zero by design
        assert per["embedding.rows"]["update_ratio"] == 0.0
        assert rec.finite() and rec.first_bad_leaf() is None
        assert monitor.get_stat("numerics_grad_norm") > 0


# ---------------------------------------------------------------------------
# prometheus export: dotted/bracketed names stay grammatical
# ---------------------------------------------------------------------------

class TestPrometheusSanitize:
    def test_per_leaf_gauge_exports_as_label(self):
        monitor.stat_set("numerics_grad_norm[fc.sub.weight]", 1.25)
        text = monitor.export_prometheus()
        assert 'numerics_grad_norm{leaf="fc.sub.weight"} 1.25' in text
        validate_prometheus(text)

    def test_dotted_name_gauge_regression(self):
        # the regression the satellite pins: a dotted-name gauge (and a
        # bracketed per-leaf path with quotes/backslashes in it) must
        # render valid exposition lines, not malformed samples
        monitor.stat_set("layer.norm.scale", 2.0)
        monitor.stat_set('numerics_max_abs_grad[w["a\\b"].0]', 3.0)
        text = monitor.export_prometheus()
        n = validate_prometheus(text)
        assert n > 0
        assert "layer_norm_scale 2.0" in text
        assert 'leaf="w[\\"a\\\\b\\"].0"' in text

    def test_nonfinite_gauge_value_renders_valid(self):
        monitor.stat_set("numerics_grad_norm", float("nan"))
        monitor.stat_set("some_inf_gauge", float("inf"))
        text = monitor.export_prometheus()
        validate_prometheus(text)
        assert "numerics_grad_norm NaN" in text
        assert "some_inf_gauge +Inf" in text


# ---------------------------------------------------------------------------
# GradScaler telemetry (satellite)
# ---------------------------------------------------------------------------

class TestGradScalerTelemetry:
    def test_scale_gauge_and_collapse_event(self):
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.framework.flags import flag
        scaler = GradScaler(enable=True, init_loss_scaling=1024.0,
                            decr_every_n_nan_or_inf=1)
        k = int(flag("numerics_scale_collapse_k"))
        for i in range(k):
            scaler._found_inf = True
            scaler.update()
        assert monitor.get_stat("amp_loss_scale") == scaler._scale
        assert scaler._scale == 1024.0 * (0.5 ** k)
        ev = flight.recent(10, kind="numerics.scale_collapse")
        assert len(ev) == 1
        assert ev[0]["attrs"]["consecutive_downscales"] == k
        assert monitor.get_stat("amp_scale_collapses_total") == 1

    def test_good_step_resets_collapse_streak(self):
        from paddle_tpu.amp import GradScaler
        scaler = GradScaler(enable=True, init_loss_scaling=1024.0,
                            decr_every_n_nan_or_inf=1)
        for _ in range(3):
            scaler._found_inf = True
            scaler.update()
            scaler._found_inf = False
            scaler.update()              # good step between downscales
        assert flight.recent(10, kind="numerics.scale_collapse") == []

    def test_resilient_scaler_coop_emits_collapse(self):
        from paddle_tpu.amp import GradScaler
        set_flags({"numerics": True})
        step, (x, z, y) = _two_branch_step()
        scaler = GradScaler(enable=True, init_loss_scaling=1024.0,
                            decr_every_n_nan_or_inf=1)
        resilient = ResilientTrainStep(step, scaler=scaler,
                                       max_consecutive_bad=8)
        chaos.arm("train.step_grads", mode="nan", every=1, n_times=4,
                  payload_index=1)
        for _ in range(5):
            resilient(x, z, y)
        assert len(flight.recent(10,
                                 kind="numerics.scale_collapse")) == 1


# ---------------------------------------------------------------------------
# per-leaf sampling cadence
# ---------------------------------------------------------------------------

class TestSampling:
    def test_per_leaf_gauges_follow_cadence(self):
        set_flags({"numerics": True, "numerics_sample_every": 3})
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        step(x, y)
        step(x, y)
        assert monitor.get_stat("numerics_grad_norm[weight]") == 0
        step(x, y)                      # 3rd publish: due
        assert monitor.get_stat("numerics_grad_norm[weight]") > 0

    def test_per_leaf_disabled_at_zero(self):
        set_flags({"numerics": True, "numerics_sample_every": 0})
        x, y = _mse_parts()
        step = _linear_step(seed=0)
        for _ in range(4):
            step(x, y)
        assert monitor.get_stat("numerics_grad_norm[weight]") == 0
        assert monitor.get_stat("numerics_grad_norm") > 0

    def test_per_leaf_zero_is_hard_off_even_on_nonfinite(self):
        """every=0 is the operator's metric-cardinality cap: even a
        non-finite step must not fan out per-leaf gauges (provenance
        still reaches the flight event via first_bad_leaf)."""
        set_flags({"numerics": True, "numerics_sample_every": 0})
        step, (x, z, y) = _two_branch_step()
        resilient = ResilientTrainStep(step)
        chaos.arm("train.step_grads", mode="nan", nth=2, n_times=1,
                  payload_index=1)
        for _ in range(3):
            resilient(x, z, y)
        assert resilient.last_bad_leaf == "aux_w"
        assert monitor.get_stat("numerics_grad_norm[aux_w]") == 0
        assert monitor.get_stat("numerics_nonfinite_steps_total") == 1
