"""paddle.distribution tier (reference tests:
python/paddle/fluid/tests/unittests/test_distribution.py — numpy-parity
of sample moments, log_prob, entropy, KL)."""
import math

import numpy as np
import pytest
from scipy import stats

import paddle_tpu as paddle
from paddle_tpu.distribution import (Categorical, Normal, Uniform,
                                     kl_divergence)


class TestUniform:
    def test_sample_range_and_moments(self):
        paddle.seed(0)
        u = Uniform(-2.0, 3.0)
        s = u.sample([20000]).numpy()
        assert s.shape == (20000,)
        assert (s >= -2.0).all() and (s < 3.0).all()
        np.testing.assert_allclose(s.mean(), 0.5, atol=0.1)
        np.testing.assert_allclose(s.std(), 5 / math.sqrt(12), atol=0.1)

    def test_batched_params(self):
        u = Uniform(np.array([0.0, 1.0], np.float32),
                    np.array([1.0, 3.0], np.float32))
        s = u.sample([500]).numpy()
        assert s.shape == (500, 2)
        assert (s[:, 1] >= 1.0).all() and (s[:, 1] < 3.0).all()

    def test_log_prob_entropy(self):
        u = Uniform(0.0, 4.0)
        np.testing.assert_allclose(
            u.log_prob(paddle.to_tensor(np.float32(1.0))).numpy(),
            math.log(0.25), rtol=1e-6)
        assert np.isneginf(
            u.log_prob(paddle.to_tensor(np.float32(5.0))).numpy())
        np.testing.assert_allclose(u.entropy().numpy(), math.log(4.0),
                                   rtol=1e-6)
        np.testing.assert_allclose(
            u.probs(paddle.to_tensor(np.float32(2.0))).numpy(), 0.25,
            rtol=1e-6)


class TestNormal:
    def test_sample_moments(self):
        paddle.seed(1)
        n = Normal(2.0, 3.0)
        s = n.sample([20000]).numpy()
        np.testing.assert_allclose(s.mean(), 2.0, atol=0.15)
        np.testing.assert_allclose(s.std(), 3.0, atol=0.15)

    def test_log_prob_matches_scipy(self):
        n = Normal(1.0, 2.0)
        v = np.array([-1.0, 0.0, 1.0, 4.0], np.float32)
        np.testing.assert_allclose(
            n.log_prob(paddle.to_tensor(v)).numpy(),
            stats.norm(1.0, 2.0).logpdf(v), rtol=1e-5)

    def test_entropy_matches_scipy(self):
        n = Normal(0.0, 2.5)
        np.testing.assert_allclose(n.entropy().numpy(),
                                   stats.norm(0, 2.5).entropy(), rtol=1e-6)

    def test_kl_closed_form(self):
        p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
        expect = (math.log(2.0) + (1.0 + 1.0) / (2 * 4.0) - 0.5)
        np.testing.assert_allclose(kl_divergence(p, q).numpy(), expect,
                                   rtol=1e-5)
        np.testing.assert_allclose(kl_divergence(p, p).numpy(), 0.0,
                                   atol=1e-7)

    def test_reparameterised_grad(self):
        """d/dμ E[x] == 1 via the pathwise sample — distributions must be
        differentiable through the tape."""
        paddle.seed(2)
        mu = paddle.to_tensor(np.float32(0.5))
        mu.stop_gradient = False
        n = Normal(mu, 1.0)
        s = n.sample([256])
        s.mean().backward()
        np.testing.assert_allclose(mu.grad.numpy(), 1.0, rtol=1e-4)


class TestCategorical:
    def test_sample_distribution(self):
        paddle.seed(3)
        c = Categorical(np.array([1.0, 2.0, 1.0], np.float32))
        s = c.sample([8000]).numpy()
        freq = np.bincount(s.reshape(-1), minlength=3) / s.size
        np.testing.assert_allclose(freq, [0.25, 0.5, 0.25], atol=0.03)

    def test_log_prob_probs(self):
        c = Categorical(np.array([1.0, 3.0], np.float32))
        lp = c.log_prob(paddle.to_tensor(np.array([0, 1]))).numpy()
        np.testing.assert_allclose(np.exp(lp), [0.25, 0.75], rtol=1e-6)
        np.testing.assert_allclose(
            c.probs(paddle.to_tensor(np.array([1]))).numpy(), [0.75],
            rtol=1e-6)

    def test_entropy_and_kl(self):
        w = np.array([1.0, 1.0, 2.0], np.float32)
        c = Categorical(w)
        p = w / w.sum()
        np.testing.assert_allclose(c.entropy().numpy(),
                                   -(p * np.log(p)).sum(), rtol=1e-5)
        c2 = Categorical(np.array([1.0, 1.0, 1.0], np.float32))
        q = np.full(3, 1 / 3)
        np.testing.assert_allclose(
            kl_divergence(c, c2).numpy(), (p * np.log(p / q)).sum(),
            rtol=1e-5)

    def test_batched_logits(self):
        logits = np.array([[1.0, 1.0], [1.0, 3.0]], np.float32)
        c = Categorical(logits)
        s = c.sample([10]).numpy()
        assert s.shape == (10, 2)
        e = c.entropy().numpy()
        assert e.shape == (2,) and e[0] > e[1]

    def test_type_errors(self):
        with pytest.raises(TypeError):
            Normal(0.0, 1.0).kl_divergence(Uniform(0.0, 1.0))
        with pytest.raises(TypeError):
            Categorical(np.ones(3, np.float32)).kl_divergence(
                Normal(0.0, 1.0))
