"""Layer tests (mirrors unittests/test_layers.py patterns)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear():
    lin = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    out = lin(x)
    assert out.shape == [2, 3]
    np.testing.assert_allclose(
        out.numpy(), x.numpy() @ lin.weight.numpy() + lin.bias.numpy(),
        atol=1e-5)


def test_conv2d_shapes():
    conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    assert conv(x).shape == [2, 8, 8, 8]
    convs = nn.Conv2D(3, 8, 3, padding="SAME")
    assert convs(x).shape == [2, 8, 16, 16]


def test_conv2d_vs_numpy():
    # 1x1 conv == matmul over channels
    conv = nn.Conv2D(3, 5, 1, bias_attr=False)
    x = paddle.randn([1, 3, 4, 4])
    out = conv(x).numpy()
    w = conv.weight.numpy()[:, :, 0, 0]  # (5, 3)
    expected = np.einsum("oc,nchw->nohw", w, x.numpy())
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_conv_grad_flows():
    conv = nn.Conv2D(2, 4, 3, padding=1)
    x = paddle.randn([1, 2, 8, 8])
    loss = conv(x).sum()
    loss.backward()
    assert conv.weight.grad is not None
    assert conv.bias.grad is not None
    assert conv.weight.grad.shape == conv.weight.shape


def test_conv2d_transpose():
    convt = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1,
                               output_padding=1)
    x = paddle.randn([1, 4, 8, 8])
    assert convt(x).shape == [1, 2, 16, 16]


def test_pooling():
    x = paddle.randn([2, 3, 8, 8])
    assert F.max_pool2d(x, 2, 2).shape == [2, 3, 4, 4]
    assert F.avg_pool2d(x, 2, 2).shape == [2, 3, 4, 4]
    assert F.adaptive_avg_pool2d(x, 1).shape == [2, 3, 1, 1]
    v = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    out = F.max_pool2d(paddle.to_tensor(v), 2, 2)
    np.testing.assert_array_equal(out.numpy()[0, 0], [[5, 7], [13, 15]])


def test_batch_norm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = paddle.to_tensor(np.random.randn(8, 4, 5, 5).astype("float32") * 3 + 1)
    bn.train()
    out = bn(x)
    # normalized output: per-channel ~0 mean, ~1 std
    o = out.numpy()
    assert abs(o.mean()) < 1e-4
    assert abs(o.std() - 1) < 1e-2
    # running stats moved toward batch stats
    assert abs(bn._mean.numpy().mean() - 0.1 * 1) < 0.5
    bn.eval()
    out_eval = bn(x)
    assert out_eval.shape == out.shape


def test_layer_norm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([4, 8])
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1, atol=1e-2)


def test_group_norm():
    gn = nn.GroupNorm(2, 4)
    x = paddle.randn([2, 4, 6, 6])
    assert gn(x).shape == [2, 4, 6, 6]


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([[1, 2, 0]], dtype="int64"))
    out = emb(ids)
    assert out.shape == [1, 3, 4]
    np.testing.assert_array_equal(out.numpy()[0, 2], np.zeros(4))
    loss = out.sum()
    loss.backward()
    assert emb.weight.grad is not None


def test_dropout():
    drop = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    drop.train()
    out = drop(x)
    frac_zero = float((out.numpy() == 0).mean())
    assert 0.4 < frac_zero < 0.6
    # upscale: surviving values are 2.0
    nz = out.numpy()[out.numpy() != 0]
    np.testing.assert_allclose(nz, 2.0)
    drop.eval()
    np.testing.assert_array_equal(drop(x).numpy(), x.numpy())


def test_activations():
    x = paddle.to_tensor([-1.0, 0.0, 1.0])
    np.testing.assert_allclose(F.relu(x).numpy(), [0, 0, 1])
    np.testing.assert_allclose(F.sigmoid(x).numpy(),
                               1 / (1 + np.exp([1, 0, -1])), rtol=1e-5)
    np.testing.assert_allclose(F.leaky_relu(x).numpy(), [-0.01, 0, 1],
                               rtol=1e-5)
    sm = F.softmax(paddle.to_tensor([[1.0, 2.0, 3.0]]))
    np.testing.assert_allclose(sm.numpy().sum(), 1.0, rtol=1e-6)


def test_losses():
    logits = paddle.to_tensor(np.random.randn(4, 5).astype("float32"))
    labels = paddle.to_tensor(np.array([0, 1, 2, 3], dtype="int64"))
    ce = nn.CrossEntropyLoss()
    loss = ce(logits, labels)
    # reference value
    z = logits.numpy()
    logp = z - np.log(np.exp(z - z.max(1, keepdims=True)).sum(1, keepdims=True)) - z.max(1, keepdims=True)
    expected = -logp[np.arange(4), [0, 1, 2, 3]].mean()
    np.testing.assert_allclose(float(loss), expected, rtol=1e-4)

    mse = nn.MSELoss()
    a, b = paddle.randn([3, 3]), paddle.randn([3, 3])
    np.testing.assert_allclose(float(mse(a, b)),
                               ((a.numpy() - b.numpy()) ** 2).mean(),
                               rtol=1e-5)


def test_sequential_and_state_dict():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    assert model(x).shape == [3, 2]
    sd = model.state_dict()
    assert "0.weight" in sd and "2.bias" in sd
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model2.set_state_dict(sd)
    np.testing.assert_array_equal(model2(x).numpy(), model(x).numpy())


def test_layer_hooks():
    lin = nn.Linear(2, 2)
    calls = []
    h1 = lin.register_forward_pre_hook(lambda l, i: calls.append("pre"))
    h2 = lin.register_forward_post_hook(lambda l, i, o: calls.append("post"))
    lin(paddle.randn([1, 2]))
    assert calls == ["pre", "post"]
    h1.remove()
    h2.remove()


def test_multi_head_attention():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 6, 16])
    out = mha(q, q, q)
    assert out.shape == [2, 6, 16]
    loss = out.sum()
    loss.backward()
    assert mha.q_proj.weight.grad is not None


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4,
                                       dim_feedforward=32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    assert enc(x).shape == [2, 5, 16]
    # clones must not share parameters
    p0 = enc.layers[0].linear1.weight.numpy()
    p1 = enc.layers[1].linear1.weight.numpy()
    assert not np.allclose(p0, p1)


def test_lstm():
    lstm = nn.LSTM(input_size=4, hidden_size=8, num_layers=2)
    x = paddle.randn([3, 7, 4])  # batch, seq, feat
    out, (h, c) = lstm(x)
    assert out.shape == [3, 7, 8]
    assert h.shape == [2, 3, 8]
    assert c.shape == [2, 3, 8]
    out.sum().backward()
    assert lstm.weight_ih_l0.grad is not None


def test_bilstm():
    lstm = nn.LSTM(input_size=4, hidden_size=8, direction="bidirect")
    x = paddle.randn([2, 5, 4])
    out, (h, c) = lstm(x)
    assert out.shape == [2, 5, 16]
    assert h.shape == [2, 2, 8]


def test_gru_cell_vs_layer():
    gru = nn.GRU(input_size=3, hidden_size=5)
    x = paddle.randn([2, 4, 3])
    out, h = gru(x)
    assert out.shape == [2, 4, 5]
    assert h.shape == [1, 2, 5]


def test_weight_norm():
    from paddle_tpu.nn.utils import weight_norm, remove_weight_norm
    lin = nn.Linear(3, 4)
    weight_norm(lin, "weight")
    assert hasattr(lin, "weight_g") and hasattr(lin, "weight_v")
    out = lin(paddle.randn([2, 3]))
    assert out.shape == [2, 4]
    remove_weight_norm(lin, "weight")
    out2 = lin(paddle.randn([2, 3]))
    assert out2.shape == [2, 4]


def test_clip_grad_by_global_norm():
    lin = nn.Linear(3, 3)
    (lin(paddle.ones([4, 3])) * 100).sum().backward()
    clip = nn.ClipGradByGlobalNorm(1.0)
    pg = clip([(p, p.grad) for p in lin.parameters()])
    total = np.sqrt(sum((g.numpy() ** 2).sum() for _, g in pg))
    np.testing.assert_allclose(total, 1.0, rtol=1e-4)
