"""Seq2seq decoding (fluid/layers/rnn.py BeamSearchDecoder/dynamic_decode
+ gather_tree op) and NCE loss (operators/nce_op.h)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core import Tensor
from paddle_tpu.nn.decode import gather_tree


class TestGatherTree:
    def test_backtrace(self):
        # T=3, B=1, W=2: reference gather_tree_op example shape
        ids = np.array([[[2, 2]], [[6, 1]], [[3, 9]]])
        parents = np.array([[[0, 0]], [[1, 1]], [[0, 0]]])
        out = gather_tree(ids, parents)
        # beam 0 at t=2 token 3, parent 0 -> t=1 token 6? parent[1,0]=1
        # walk: t2 w0: ids=3, parent=0; t1 from parent chain
        assert out.shape == (3, 1, 2)
        np.testing.assert_array_equal(out[2, 0], [3, 9])


class _CounterCell:
    """Deterministic 'cell': logits favor (last_token + 1) % V, so the
    best beam is the counting sequence and beam search must find it."""

    def __init__(self, vocab, hidden=4):
        self.vocab = vocab

    def __call__(self, inputs, states):
        ids = np.asarray(inputs.numpy()).astype(np.int64).reshape(-1)
        logits = np.full((ids.size, self.vocab), -5.0, np.float32)
        nxt = (ids + 1) % self.vocab
        logits[np.arange(ids.size), nxt] = 5.0
        # second-best: same token again (worse score)
        logits[np.arange(ids.size), ids] = 2.0
        return Tensor(logits), states


class TestBeamSearch:
    def test_counting_sequence_wins(self):
        V, B, W = 7, 2, 3
        end = V - 1
        cell = _CounterCell(V)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=end,
                                   beam_size=W)
        inits = Tensor(np.zeros((B, 4), np.float32))
        out, state, lengths = nn.dynamic_decode(dec, inits,
                                                max_step_num=10,
                                                return_length=True)
        pred = out.numpy()                       # [B, T, W]
        assert pred.shape[0] == B and pred.shape[2] == W
        # best beam counts 1,2,3,...,end then freezes on end_token while
        # worse beams finish
        np.testing.assert_array_equal(pred[0, :end, 0],
                                      np.arange(1, end + 1))
        assert (pred[0, end:, 0] == end).all()
        assert lengths.numpy()[0, 0] == end      # length up to end token

    def test_finished_beams_freeze(self):
        V, end = 4, 3
        cell = _CounterCell(V)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=end,
                                   beam_size=2)
        inits = Tensor(np.zeros((1, 4), np.float32))
        out, state = nn.dynamic_decode(dec, inits, max_step_num=8)
        pred = out.numpy()[0, :, 0]
        # after reaching end (token 3), only end_token repeats
        first_end = int(np.argmax(pred == end))
        assert (pred[first_end:] == end).all()

    def test_tile_beam_merge(self):
        x = Tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        t = nn.BeamSearchDecoder.tile_beam_merge_with_batch(x, 2)
        np.testing.assert_allclose(
            t.numpy(), [[0, 1, 2], [0, 1, 2], [3, 4, 5], [3, 4, 5]])


class TestNCELoss:
    def test_shape_and_positive(self):
        nce = nn.NCELoss(num_total_classes=50, dim=8, num_neg_samples=5)
        x = paddle.to_tensor(np.random.default_rng(0)
                             .standard_normal((6, 8)).astype(np.float32))
        y = paddle.to_tensor(np.arange(6, dtype=np.int64)[:, None])
        loss = nce(x, y)
        assert list(loss.shape) == [6, 1]
        assert (loss.numpy() > 0).all()

    def test_trains_vs_full_softmax_task(self):
        """NCE on a 4-class linearly separable task approaches the true
        class: loss falls and the true-class score dominates."""
        paddle.seed(0)
        rng = np.random.default_rng(1)
        V, D, B = 16, 8, 64
        proj = nn.Linear(4, D)
        nce = nn.NCELoss(V, D, num_neg_samples=4, seed=2)
        opt = paddle.optimizer.Adam(
            learning_rate=0.05,
            parameters=proj.parameters() + nce.parameters())
        x = rng.standard_normal((B, 4)).astype(np.float32)
        y = x.argmax(1).astype(np.int64)[:, None]
        losses = []
        for _ in range(60):
            loss = nce(proj(paddle.to_tensor(x)),
                       paddle.to_tensor(y)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # inference-time full scores rank the true class first mostly
        feats = proj(paddle.to_tensor(x)).numpy()
        scores = feats @ nce.weight.numpy().T + nce.bias.numpy()
        acc = (scores.argmax(1) == y[:, 0]).mean()
        assert acc > 0.7, acc

    def test_unsupported_sampler(self):
        with pytest.raises(NotImplementedError):
            nn.NCELoss(10, 4, sampler="log_uniform")
