"""jit.to_static / TrainStep / jit.save+load tests.

The reference tests this surface heavily (test_jit_save_load.py,
dygraph_to_static/test_*): forward parity eager-vs-captured, backward
through the captured block, shape-keyed recompilation, save/load
roundtrip.  Reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:756.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import jit


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _np(t):
    return np.asarray(t.numpy())


class TestToStatic:
    def test_forward_parity_vs_eager(self):
        paddle.seed(0)
        net = SmallNet()
        x = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(3, 8)).astype(np.float32))
        eager_out = _np(net(x))
        static_net = jit.to_static(SmallNet())
        static_net.set_state_dict(net.state_dict())
        out = _np(static_net(x))
        np.testing.assert_allclose(out, eager_out, rtol=1e-5, atol=1e-5)

    def test_backward_through_capture(self):
        paddle.seed(0)
        net_e = SmallNet()
        net_s = jit.to_static(SmallNet())
        net_s.set_state_dict(net_e.state_dict())
        x = paddle.to_tensor(
            np.random.default_rng(2).normal(size=(4, 8)).astype(np.float32))

        loss_e = net_e(x).sum()
        loss_e.backward()
        loss_s = net_s(x).sum()
        loss_s.backward()

        np.testing.assert_allclose(float(loss_s), float(loss_e),
                                   rtol=1e-5, atol=1e-5)
        ge = {n: _np(p.grad) for n, p in net_e.named_parameters()}
        gs = {n: _np(p.grad) for n, p in net_s.named_parameters()}
        assert set(ge) == set(gs)
        for n in ge:
            np.testing.assert_allclose(gs[n], ge[n], rtol=1e-5, atol=1e-5,
                                       err_msg=n)

    def test_recompile_on_new_shape(self):
        net = jit.to_static(SmallNet())
        x1 = paddle.to_tensor(np.zeros((2, 8), np.float32))
        x2 = paddle.to_tensor(np.zeros((5, 8), np.float32))
        net(x1)
        sf = net.forward
        n_after_first = len(sf._cache)
        net(x1)
        assert len(sf._cache) == n_after_first  # cache hit
        net(x2)
        assert len(sf._cache) == n_after_first + 1  # recompiled

    def test_plain_function_capture(self):
        @jit.to_static
        def f(x, y):
            return x * y + 2.0

        a = paddle.to_tensor(np.arange(4, dtype=np.float32))
        b = paddle.to_tensor(np.ones(4, np.float32) * 3)
        out = _np(f(a, b))
        np.testing.assert_allclose(out, np.arange(4) * 3.0 + 2.0, rtol=1e-6)

    def test_training_flag_in_cache_key(self):
        class DropNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return F.dropout(self.fc(x), p=0.5,
                                 training=self.training)

        net = jit.to_static(DropNet())
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        net.train()
        net(x)
        net.eval()
        out1 = _np(net(x))
        out2 = _np(net(x))
        np.testing.assert_allclose(out1, out2)  # eval: deterministic


class TestSaveLoad:
    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(0)
        net = SmallNet()
        net.eval()
        x = paddle.to_tensor(
            np.random.default_rng(3).normal(size=(2, 8)).astype(np.float32))
        ref = _np(net(x))
        path = str(tmp_path / "model")
        spec = [paddle.static.InputSpec(shape=[2, 8], dtype="float32")] \
            if hasattr(paddle.static, "InputSpec") else None
        if spec is None:
            pytest.skip("no InputSpec")
        jit.save(net, path, input_spec=spec)
        loaded = jit.load(path)
        out = _np(loaded(x))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestTrainStep:
    def test_trainstep_matches_eager_sgd(self):
        paddle.seed(0)
        net_e = SmallNet()
        net_s = SmallNet()
        net_s.set_state_dict(net_e.state_dict())
        x = paddle.to_tensor(
            np.random.default_rng(4).normal(size=(4, 8)).astype(np.float32))
        y = paddle.to_tensor(
            np.random.default_rng(5).normal(size=(4, 4)).astype(np.float32))

        opt_e = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_e.parameters())
        opt_s = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_s.parameters())

        def loss_fn(model, xb, yb):
            return ((model(xb) - yb) ** 2).mean()

        step = jit.TrainStep(net_s, loss_fn, opt_s)
        for _ in range(3):
            loss_e = loss_fn(net_e, x, y)
            loss_e.backward()
            opt_e.step()
            opt_e.clear_grad()
            loss_s = step(x, y)
            np.testing.assert_allclose(float(loss_s), float(loss_e),
                                       rtol=1e-4, atol=1e-5)
        for (n, pe), (_, ps) in zip(net_e.named_parameters(),
                                    net_s.named_parameters()):
            np.testing.assert_allclose(_np(ps), _np(pe),
                                       rtol=1e-4, atol=1e-5, err_msg=n)


class TestMultiStep:
    def test_multi_step_matches_sequential_steps(self):
        """K scanned steps in one dispatch == K individual __call__ steps
        (deterministic net: no dropout, so RNG key threading is moot)."""
        paddle.seed(0)
        net_a = SmallNet()
        net_b = SmallNet()
        net_b.set_state_dict(net_a.state_dict())
        init_state = {k: np.asarray(v.numpy())
                      for k, v in net_a.state_dict().items()}
        K = 4
        rng = np.random.default_rng(7)
        xs = rng.normal(size=(K, 4, 8)).astype(np.float32)
        ys = rng.normal(size=(K, 4, 4)).astype(np.float32)

        def loss_fn(model, xb, yb):
            return ((model(xb) - yb) ** 2).mean()

        opt_a = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=net_a.parameters())
        opt_b = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=net_b.parameters())
        step_a = jit.TrainStep(net_a, loss_fn, opt_a)
        step_b = jit.TrainStep(net_b, loss_fn, opt_b)

        seq_losses = [float(step_a(paddle.to_tensor(xs[i]),
                                   paddle.to_tensor(ys[i])))
                      for i in range(K)]
        multi_losses = step_b.multi_step(paddle.to_tensor(xs),
                                         paddle.to_tensor(ys))
        assert multi_losses.shape == [K]

        np.testing.assert_allclose(np.asarray(multi_losses.numpy()),
                                   np.asarray(seq_losses),
                                   rtol=1e-4, atol=1e-5)
        for (n, pa), (_, pb) in zip(net_a.named_parameters(),
                                    net_b.named_parameters()):
            np.testing.assert_allclose(_np(pb), _np(pa),
                                       rtol=1e-4, atol=1e-5, err_msg=n)
        assert opt_b._global_step == K
        # the straight-line (unroll=True) variant must match too
        paddle.seed(0)
        net_c = SmallNet()
        net_c.set_state_dict(
            {k: paddle.to_tensor(v) for k, v in init_state.items()})
        opt_c = paddle.optimizer.Adam(learning_rate=0.05,
                                      parameters=net_c.parameters())
        step_c = jit.TrainStep(net_c, loss_fn, opt_c)
        unrolled = step_c.multi_step(paddle.to_tensor(xs),
                                     paddle.to_tensor(ys), unroll=True)
        np.testing.assert_allclose(np.asarray(unrolled.numpy()),
                                   np.asarray(seq_losses),
                                   rtol=1e-4, atol=1e-5)

    def test_multi_step_amp_runs(self):
        paddle.seed(1)
        net = SmallNet()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=net.parameters())

        def loss_fn(model, xb, yb):
            return ((model(xb) - yb) ** 2).mean()

        step = jit.TrainStep(net, loss_fn, opt, amp_level="O2",
                             amp_dtype="bfloat16")
        rng = np.random.default_rng(3)
        xs = paddle.to_tensor(rng.normal(size=(3, 2, 8)).astype(np.float32))
        ys = paddle.to_tensor(rng.normal(size=(3, 2, 4)).astype(np.float32))
        losses = step.multi_step(xs, ys)
        assert losses.shape == [3]
        assert np.all(np.isfinite(np.asarray(losses.numpy())))

    def test_multi_step_with_gradient_merge(self):
        """accumulate_steps flows through the device loop: K scanned steps
        each doing micro-batch gradient-merge == K individual calls."""
        paddle.seed(2)
        net_a = SmallNet()
        net_b = SmallNet()
        net_b.set_state_dict(net_a.state_dict())
        K, micro = 3, 2
        rng = np.random.default_rng(11)
        xs = rng.normal(size=(K, 4, 8)).astype(np.float32)
        ys = rng.normal(size=(K, 4, 4)).astype(np.float32)

        def loss_fn(model, xb, yb):
            return ((model(xb) - yb) ** 2).mean()

        opt_a = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_a.parameters())
        opt_b = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_b.parameters())
        step_a = jit.TrainStep(net_a, loss_fn, opt_a,
                               accumulate_steps=micro)
        step_b = jit.TrainStep(net_b, loss_fn, opt_b,
                               accumulate_steps=micro)
        seq = [float(step_a(paddle.to_tensor(xs[i]),
                            paddle.to_tensor(ys[i]))) for i in range(K)]
        multi = step_b.multi_step(paddle.to_tensor(xs),
                                  paddle.to_tensor(ys))
        np.testing.assert_allclose(np.asarray(multi.numpy()),
                                   np.asarray(seq), rtol=1e-4, atol=1e-5)
        for (n, pa), (_, pb) in zip(net_a.named_parameters(),
                                    net_b.named_parameters()):
            np.testing.assert_allclose(_np(pb), _np(pa),
                                       rtol=1e-4, atol=1e-5, err_msg=n)
