"""jit.to_static / TrainStep / jit.save+load tests.

The reference tests this surface heavily (test_jit_save_load.py,
dygraph_to_static/test_*): forward parity eager-vs-captured, backward
through the captured block, shape-keyed recompilation, save/load
roundtrip.  Reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:756.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu import jit


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _np(t):
    return np.asarray(t.numpy())


class TestToStatic:
    def test_forward_parity_vs_eager(self):
        paddle.seed(0)
        net = SmallNet()
        x = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(3, 8)).astype(np.float32))
        eager_out = _np(net(x))
        static_net = jit.to_static(SmallNet())
        static_net.set_state_dict(net.state_dict())
        out = _np(static_net(x))
        np.testing.assert_allclose(out, eager_out, rtol=1e-5, atol=1e-5)

    def test_backward_through_capture(self):
        paddle.seed(0)
        net_e = SmallNet()
        net_s = jit.to_static(SmallNet())
        net_s.set_state_dict(net_e.state_dict())
        x = paddle.to_tensor(
            np.random.default_rng(2).normal(size=(4, 8)).astype(np.float32))

        loss_e = net_e(x).sum()
        loss_e.backward()
        loss_s = net_s(x).sum()
        loss_s.backward()

        np.testing.assert_allclose(float(loss_s), float(loss_e),
                                   rtol=1e-5, atol=1e-5)
        ge = {n: _np(p.grad) for n, p in net_e.named_parameters()}
        gs = {n: _np(p.grad) for n, p in net_s.named_parameters()}
        assert set(ge) == set(gs)
        for n in ge:
            np.testing.assert_allclose(gs[n], ge[n], rtol=1e-5, atol=1e-5,
                                       err_msg=n)

    def test_recompile_on_new_shape(self):
        net = jit.to_static(SmallNet())
        x1 = paddle.to_tensor(np.zeros((2, 8), np.float32))
        x2 = paddle.to_tensor(np.zeros((5, 8), np.float32))
        net(x1)
        sf = net.forward
        n_after_first = len(sf._cache)
        net(x1)
        assert len(sf._cache) == n_after_first  # cache hit
        net(x2)
        assert len(sf._cache) == n_after_first + 1  # recompiled

    def test_plain_function_capture(self):
        @jit.to_static
        def f(x, y):
            return x * y + 2.0

        a = paddle.to_tensor(np.arange(4, dtype=np.float32))
        b = paddle.to_tensor(np.ones(4, np.float32) * 3)
        out = _np(f(a, b))
        np.testing.assert_allclose(out, np.arange(4) * 3.0 + 2.0, rtol=1e-6)

    def test_training_flag_in_cache_key(self):
        class DropNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                return F.dropout(self.fc(x), p=0.5,
                                 training=self.training)

        net = jit.to_static(DropNet())
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        net.train()
        net(x)
        net.eval()
        out1 = _np(net(x))
        out2 = _np(net(x))
        np.testing.assert_allclose(out1, out2)  # eval: deterministic


class TestSaveLoad:
    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(0)
        net = SmallNet()
        net.eval()
        x = paddle.to_tensor(
            np.random.default_rng(3).normal(size=(2, 8)).astype(np.float32))
        ref = _np(net(x))
        path = str(tmp_path / "model")
        spec = [paddle.static.InputSpec(shape=[2, 8], dtype="float32")] \
            if hasattr(paddle.static, "InputSpec") else None
        if spec is None:
            pytest.skip("no InputSpec")
        jit.save(net, path, input_spec=spec)
        loaded = jit.load(path)
        out = _np(loaded(x))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


class TestTrainStep:
    def test_trainstep_matches_eager_sgd(self):
        paddle.seed(0)
        net_e = SmallNet()
        net_s = SmallNet()
        net_s.set_state_dict(net_e.state_dict())
        x = paddle.to_tensor(
            np.random.default_rng(4).normal(size=(4, 8)).astype(np.float32))
        y = paddle.to_tensor(
            np.random.default_rng(5).normal(size=(4, 4)).astype(np.float32))

        opt_e = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_e.parameters())
        opt_s = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_s.parameters())

        def loss_fn(model, xb, yb):
            return ((model(xb) - yb) ** 2).mean()

        step = jit.TrainStep(net_s, loss_fn, opt_s)
        for _ in range(3):
            loss_e = loss_fn(net_e, x, y)
            loss_e.backward()
            opt_e.step()
            opt_e.clear_grad()
            loss_s = step(x, y)
            np.testing.assert_allclose(float(loss_s), float(loss_e),
                                       rtol=1e-4, atol=1e-5)
        for (n, pe), (_, ps) in zip(net_e.named_parameters(),
                                    net_s.named_parameters()):
            np.testing.assert_allclose(_np(ps), _np(pe),
                                       rtol=1e-4, atol=1e-5, err_msg=n)
