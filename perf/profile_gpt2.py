import numpy as np
import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.models import GPT, gpt2_345m, gpt_loss
import jax
import time


def fence(t):
    np.asarray(t._data if hasattr(t, "_data") else t)


B, S = 8, 1024
cfg = gpt2_345m(remat=False, max_seq_len=S, scan_unroll=24)
model = GPT(cfg)
opt = optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
step = TrainStep(model, gpt_loss, opt, amp_level="O2", amp_dtype="bfloat16")
rng = np.random.default_rng(0)
ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size,
                                    size=(B, S)).astype(np.int32))
for _ in range(3):
    loss = step(ids, ids)
fence(loss)
t0 = time.perf_counter()
for _ in range(10):
    loss = step(ids, ids)
fence(loss)
dt = time.perf_counter() - t0
print(f"step={dt/10*1000:.1f}ms tok/s={B*S*10/dt:.0f}")
with jax.profiler.trace("/tmp/gpttrace"):
    for _ in range(5):
        loss = step(ids, ids)
    fence(loss)
print("trace captured")
import subprocess
print(subprocess.run(["find", "/tmp/gpttrace", "-type", "f"],
                     capture_output=True, text=True).stdout)
