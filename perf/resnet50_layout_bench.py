import time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.vision.models import resnet50

def fence(t):
    np.asarray(t._data if hasattr(t, "_data") else t)

B, HW = 128, 224
rng = np.random.default_rng(0)
x_nchw = rng.standard_normal((B, 3, HW, HW)).astype(np.float32)
y = paddle.to_tensor(rng.integers(0, 1000, size=(B,)).astype(np.int64))

def bench(data_format):
    model = resnet50(num_classes=1000, data_format=data_format)
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    def loss_fn(m, xb, yb):
        return F.cross_entropy(m(xb), yb).mean()
    step = TrainStep(model, loss_fn, opt, amp_level="O2",
                     amp_dtype="bfloat16")
    xin = x_nchw if data_format == "NCHW" else \
        np.ascontiguousarray(x_nchw.transpose(0, 2, 3, 1))
    xt = paddle.to_tensor(xin)
    for _ in range(3):
        loss = step(xt, y)
    fence(loss)
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(xt, y)
    fence(loss)
    dt = (time.perf_counter() - t0) / iters
    sps = B / dt
    print(f"{data_format}: {dt*1e3:.1f} ms/step  {sps:.0f} samples/s")
    return sps

s1 = bench("NCHW")
s2 = bench("NHWC")
print(f"NHWC speedup: {s2/s1:.2f}x")
