import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.vision.models import resnet50
import jax

def fence(t): np.asarray(t._data if hasattr(t, "_data") else t)

B, HW = 128, 224
rng = np.random.default_rng(0)
model = resnet50(num_classes=1000)
opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=model.parameters())
def loss_fn(m, xb, yb):
    return F.cross_entropy(m(xb), yb).mean()
step = TrainStep(model, loss_fn, opt, amp_level="O2", amp_dtype="bfloat16")
x = paddle.to_tensor(rng.standard_normal((B, 3, HW, HW)).astype(np.float32))
y = paddle.to_tensor(rng.integers(0, 1000, size=(B,)).astype(np.int64))
for _ in range(3):
    loss = step(x, y)
fence(loss)
with jax.profiler.trace("/tmp/jaxtrace"):
    for _ in range(5):
        loss = step(x, y)
    fence(loss)
print("trace captured")
import subprocess
print(subprocess.run(["find", "/tmp/jaxtrace", "-name", "*.pb*", "-o", "-name", "*.json*"],
                     capture_output=True, text=True).stdout)
