"""File-fed ingest worker-pool slope (round-4 verdict weak 6).

``perf/filefed_analysis.md`` §2 argues from arithmetic that ~50-110
host cores sustain chip-rate JPEG ingest through the multiprocess
DataLoader — but no bench leg ever spun the worker pool up.  This
script measures the loader-only drain rate of the same
DatasetFolder+transform stack at num_workers ∈ {0, 1, 2} and appends
the measured per-worker slope to the analysis.

This host has ONE vCPU, so absolute aggregate throughput cannot rise
past one core's rate; what the 2-worker leg shows is the *overhead
slope*: aggregate examples/s at 2 procs vs 1 proc vs in-process — i.e.
how much of a worker's core actually turns into ingest once IPC,
pickling, and the bounded buffer take their cut.  That efficiency
factor is exactly the number the analysis' core-count arithmetic was
missing.

Reference role: python/paddle/fluid/reader.py DataLoader worker pool +
framework/data_feed.cc multi-thread ingest.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.getcwd())

# CPU-only: ingest never touches the accelerator, and a tunnel probe
# would serialize with any chip job running alongside
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def build_dataset(hw=96, n_img=256):
    from bench import _gen_image_dataset
    from paddle_tpu.vision import transforms as T
    from paddle_tpu.vision.datasets import DatasetFolder

    root = f"/tmp/paddle_tpu_worker_scaling_{hw}_{n_img}"
    _gen_image_dataset(root, n_img, hw + 32, 10)

    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)

    def to_chw_norm(img):
        arr = np.asarray(img, np.float32) / 255.0
        return ((arr - mean) / std).transpose(2, 0, 1)

    tf = T.Compose([T.RandomResizedCrop(hw), T.RandomHorizontalFlip(),
                    to_chw_norm])

    def pil_loader(path):
        from PIL import Image
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    return DatasetFolder(root, loader=pil_loader, extensions=(".jpg",),
                        transform=tf)


def drain(ds, num_workers, batch_size=32, repeats=2):
    from paddle_tpu.io import DataLoader
    best = 0.0
    for _ in range(repeats):
        loader = DataLoader(ds, batch_size=batch_size, shuffle=False,
                            drop_last=False, num_workers=num_workers)
        n = 0
        t0 = time.perf_counter()
        for xb, yb in loader:
            n += int(xb.shape[0])
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    return best


def main():
    ds = build_dataset()
    rows = []
    for w in (0, 1, 2):
        rate = drain(ds, w)
        rows.append({"num_workers": w, "examples_per_sec": round(rate, 1)})
        print(json.dumps(rows[-1]), flush=True)

    r0, r1, r2 = (r["examples_per_sec"] for r in rows)
    eff1 = r1 / r0 if r0 else 0.0       # 1 worker proc vs in-process
    # 2 procs share the single core: their aggregate vs 1 proc measures
    # the added IPC/scheduling cost, not parallel speedup
    agg2 = r2 / r1 if r1 else 0.0
    para = [
        "",
        "### Measured worker-pool slope (round 5)",
        "",
        "| num_workers | ingest (examples/s) |",
        "|---|---|",
    ] + [f"| {r['num_workers']} | {r['examples_per_sec']} |" for r in rows] + [
        "",
        f"One 1-vCPU host, 96px RandomResizedCrop pipeline.  A single "
        f"worker process delivers **{eff1:.2f}×** the in-process rate "
        "(net of the IPC + pickling tax and the decode/batch-assembly "
        "overlap a worker buys) and two processes "
        f"time-slicing the same core aggregate to **{agg2:.2f}×** the "
        "one-worker rate (≈1.0 means the pool scheduling itself costs "
        "nothing; the core is the only bottleneck).  Folding the "
        "efficiency factor into §2's arithmetic: the projected core "
        "count for chip-rate ingest scales by 1/efficiency — e.g. at "
        f"{eff1:.2f} efficiency the ~50-110-core estimate becomes "
        f"~{int(round(50 / max(eff1, 1e-9)))}-"
        f"{int(round(110 / max(eff1, 1e-9)))} cores.",
    ]
    path = os.path.join(os.path.dirname(__file__), "filefed_analysis.md")
    with open(path) as f:
        txt = f.read()
    marker = "### Measured worker-pool slope (round 5)"
    if marker in txt:
        txt = txt[:txt.index(marker)].rstrip() + "\n"
        txt += "\n".join(para[1:]) + "\n"
    else:
        txt = txt.rstrip() + "\n" + "\n".join(para) + "\n"
    with open(path, "w") as f:
        f.write(txt)
    print(f"appended slope section to {path}")


if __name__ == "__main__":
    main()
