#!/usr/bin/env python
"""ResNet-50 HBM-bandwidth ledger v2 (round-4 verdict item 6).

Round 3's analysis summed the profiler's ``bytes_accessed``, which counts
HLO-level operand accesses — a figure that EXCEEDS physical HBM traffic
whenever operands are re-read from VMEM/caches inside a fusion (hence
"achieved 970 GB/s / 2.26 TB/s" against an 819 GB/s part).

This ledger computes the opposite bound from the TPU-optimized HLO of
the exact bench step: for every top-level instruction in the entry
computation, HBM bytes >= unique operand bytes + output bytes (fusion
internals live in VMEM/registers by construction).  Summing gives the
*minimum* HBM traffic the compiled schedule can do — a floor, stated in
bytes that must each cross HBM exactly once.

floor_time = floor_bytes / 819 GB/s is then directly comparable to the
measured step: measured/floor ≈ 1 ⇒ at the roofline.

Run from the repo root:  python - < perf/resnet50_ledger.py
"""
from __future__ import annotations

import re
import sys
import os

sys.path.insert(0, os.getcwd())

import numpy as np


DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
               "s64": 8, "u64": 8, "pred": 1, "s8": 1, "u8": 1,
               "f64": 8, "s16": 2, "u16": 2}

SHAPE_RE = re.compile(r"\b(f32|bf16|f16|f64|s8|u8|s16|u16|s32|u32|s64|u64|"
                      r"pred)\[([0-9,]*)\]")


def shape_bytes(tok: str) -> int:
    total = 0
    for dt, dims in SHAPE_RE.findall(tok):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * DTYPE_BYTES[dt]
    return total


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    B, HW = 128, 224
    model = resnet50(num_classes=1000)
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())

    def loss_fn(m, x, y):
        return F.cross_entropy(m(x), y).mean()

    step = TrainStep(model, loss_fn, opt, amp_level="O2",
                     amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((B, 3, HW, HW))
                         .astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 1000, size=(B,)).astype(np.int64))
    loss = step(x, y)                       # compile + one step
    np.asarray(loss._data)
    hlo = step.compiled_text()

    # find the ENTRY computation (largest region is fine: parse every
    # computation but attribute only the entry's top-level instructions)
    entry = None
    blocks = re.split(r"\n(?=ENTRY |%?\w[\w.\-]* \()", hlo)
    for b in blocks:
        if b.startswith("ENTRY"):
            entry = b
            break
    if entry is None:                       # fall back: whole text
        entry = hlo

    per_cat = {}
    total = 0
    n_inst = 0
    for line in entry.splitlines():
        line = line.strip()
        m = re.match(r"%?([\w.\-]+) = (.+)", line)
        if not m or "ROOT" in line.split("=")[0]:
            pass
        if not m:
            continue
        name, rhs = m.groups()
        if "(" not in rhs:
            continue
        # output shape(s): the type token(s) before the op name
        opm = re.match(r"(\(?[a-z0-9\[\],\s]+\)?)\s+([a-z\-]+)", rhs)
        if not opm:
            continue
        out_tok, op = opm.groups()
        if op in ("parameter", "constant"):
            continue
        out_b = shape_bytes(out_tok)
        # operand shapes: HLO text repeats operand types inline only in
        # some dialects; in the common form operands are %names — resolve
        # via a shape table built from all definitions
        total += out_b
        n_inst += 1
        per_cat[op] = per_cat.get(op, 0) + out_b

    # second pass: operand bytes via definition table
    defs = {}
    for line in entry.splitlines():
        line = line.strip()
        m = re.match(r"%?([\w.\-]+) = (\(?[a-z0-9\[\],\s]+\)?)\s", line)
        if m:
            defs[m.group(1)] = shape_bytes(m.group(2))
    operand_total = 0
    for line in entry.splitlines():
        line = line.strip()
        m = re.match(r"%?([\w.\-]+) = (.+)", line)
        if not m:
            continue
        rhs = m.group(2)
        opm = re.match(r"(\(?[a-z0-9\[\],\s]+\)?)\s+([a-z\-]+)", rhs)
        if not opm or opm.group(2) in ("parameter", "constant"):
            continue
        args = re.findall(r"%([\w.\-]+)", rhs)
        seen = set()
        for a in args:
            if a in defs and a not in seen:
                seen.add(a)
                operand_total += defs[a]

    gb_out = total / 1e9
    gb_in = operand_total / 1e9
    gb_floor = gb_out + gb_in
    print(f"instructions: {n_inst}")
    print(f"output bytes (write floor): {gb_out:.2f} GB")
    print(f"operand bytes (read floor): {gb_in:.2f} GB")
    print(f"HBM floor: {gb_floor:.2f} GB  -> "
          f"{gb_floor / 819 * 1000:.1f} ms at 819 GB/s")
    print("top categories by output bytes:")
    for op, b in sorted(per_cat.items(), key=lambda kv: -kv[1])[:12]:
        print(f"  {op:28s} {b/1e9:7.2f} GB")


if __name__ == "__main__":
    main()
