"""DGC exchange vs dense psum — the crossover measurement.

VERDICT r4 weak 7: ``DGCTrainStep.compress`` reconstructs a dense buffer
per tensor per step (`parallel/dp_meta.py`), and its docstring asserts
"on a single-pod ICI mesh a dense psum is usually faster" without a
number.  This script grounds that guidance: it times the two exchange
strategies in isolation (no model, no optimizer) at 1M/10M/100M-element
tensors on the virtual dp=8 CPU mesh and writes
``perf/dgc_crossover.md``.

What each arm does, per tensor, per step:

  dense:  g_bar = pmean(g)                       wire: size * 4 bytes
  dgc:    k = size*(1-sparsity); top_k(|v|);      wire: k * 8 * dp bytes
          all_gather(vals, idx); scatter-add
          into a dense zeros buffer; error-
          feedback writes back into u, v

The *wire* term is what DGC is for (DCN-connected hosts); the compute
term (top_k + the dense reconstruction) is what it costs.  On a CPU
mesh the "wire" is memcpy, so this measures the compute/memory side of
the crossover — the side weak 7 said was unmeasured.  Pass ``--chip``
to run on the real accelerator instead (dp=1 there, so the chip row is
the single-shard compute cost only).

Reference role: paddle/fluid/operators/dgc_op.* (the CUDA compress
kernels) + framework/details/dgc helpers.
"""
from __future__ import annotations

import json
import os
import sys
import time

if "--chip" not in sys.argv:
    # the virtual 8-device mesh is the default; the env var alone is NOT
    # honored once the accelerator plugin registers, so force it through
    # jax.config too (same dance as tests/conftest.py)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_count=8")

import jax

if "--chip" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SIZES = [1_000_000, 10_000_000, 100_000_000]
SPARSITY = 0.999


def _time(fn, *args, reps=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def build(mesh, size, dp):
    k = max(1, int(round(size * (1.0 - SPARSITY))))

    def dense_local(g):
        return jax.lax.pmean(g.astype(jnp.float32), "dp")

    def dgc_local(g, u, v):
        # the exact exchange pipeline from parallel/dp_meta.py::compress
        g = g.astype(jnp.float32)
        u = 0.9 * u + g
        v = v + u
        flat = v.reshape(-1)
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        vals = flat[idx]
        g_vals = jax.lax.all_gather(vals, "dp")
        g_idx = jax.lax.all_gather(idx, "dp")
        dense = jnp.zeros((size,), jnp.float32).at[
            g_idx.reshape(-1)].add(g_vals.reshape(-1)) / dp
        flat_v = flat.at[idx].set(0.0)
        flat_u = u.reshape(-1).at[idx].set(0.0)
        return dense, flat_u, flat_v

    specs_g = (P("dp"),)
    dense_fn = jax.jit(shard_map(
        lambda g: dense_local(g[0])[None],
        mesh=mesh, in_specs=specs_g, out_specs=P("dp"), check_vma=False))
    dgc_fn = jax.jit(shard_map(
        lambda g, u, v: tuple(
            o[None] for o in dgc_local(g[0], u[0], v[0])),
        mesh=mesh, in_specs=(P("dp"),) * 3,
        out_specs=(P("dp"),) * 3, check_vma=False),
        donate_argnums=(1, 2))
    return dense_fn, dgc_fn, k


def main():
    devs = jax.devices()
    dp = len(devs)
    mesh = Mesh(np.array(devs), ("dp",))
    shard = NamedSharding(mesh, P("dp"))
    rows = []
    for size in SIZES:
        rng = np.random.default_rng(size)
        g = jax.device_put(
            rng.standard_normal((dp, size), dtype=np.float32), shard)
        u = jax.device_put(jnp.zeros((dp, size), jnp.float32), shard)
        v = jax.device_put(jnp.zeros((dp, size), jnp.float32), shard)
        dense_fn, dgc_fn, k = build(mesh, size, dp)
        reps = 5 if size < 100_000_000 else 2
        t_dense = _time(dense_fn, g, reps=reps)

        # donation consumes u/v: thread each rep's outputs back in as the
        # next rep's inputs instead of re-feeding the consumed buffers
        out = dgc_fn(g, u, v)
        jax.block_until_ready(out)
        _, u2, v2 = out
        t0 = time.perf_counter()
        for _ in range(reps):
            out = dgc_fn(g, u2, v2)
            _, u2, v2 = out
        jax.block_until_ready(out)
        t_dgc = (time.perf_counter() - t0) / reps

        wire_dense = size * 4
        wire_dgc = k * 8 * dp
        rows.append({
            "size": size, "k": k, "dp": dp,
            "dense_ms": round(t_dense * 1e3, 2),
            "dgc_ms": round(t_dgc * 1e3, 2),
            "dgc_over_dense": round(t_dgc / t_dense, 2),
            "wire_dense_mb": round(wire_dense / 1e6, 2),
            "wire_dgc_mb": round(wire_dgc / 1e6, 3),
            "wire_ratio": round(wire_dense / wire_dgc, 1),
        })
        print(json.dumps(rows[-1]), flush=True)
        del g, u, v, out, u2, v2

    md = ["# DGC exchange vs dense psum — measured crossover",
          "",
          f"Virtual dp={dp} CPU mesh ({jax.devices()[0].platform}), "
          f"sparsity={SPARSITY} (k=size/1000), per-tensor pipeline "
          "identical to `parallel/dp_meta.py::compress`.",
          "",
          "| elements | dense psum (ms) | DGC exchange (ms) | DGC/dense | "
          "wire dense (MB) | wire DGC (MB) | wire saving |",
          "|---|---|---|---|---|---|---|"]
    for r in rows:
        md.append(
            f"| {r['size']:,} | {r['dense_ms']} | {r['dgc_ms']} | "
            f"{r['dgc_over_dense']}× | {r['wire_dense_mb']} | "
            f"{r['wire_dgc_mb']} | {r['wire_ratio']}× |")
    worst = max(r["dgc_over_dense"] for r in rows)
    best = min(r["dgc_over_dense"] for r in rows)
    best_wire = max(r["wire_ratio"] for r in rows)
    md += ["",
           "**Conclusion.** The compute side of DGC (top-k over the "
           "error accumulator + dense scatter-add reconstruction) costs "
           f"{best}–{worst}× a dense psum at these "
           "sizes on this mesh, while the wire payload shrinks "
           f"~{best_wire:.0f}×.  That is the crossover the "
           "`DGCTrainStep` docstring asserts: on an ICI-connected pod, "
           "where the dense all-reduce rides ~100s of GB/s links, pay "
           "the dense psum; DGC wins only when the interconnect is the "
           "bottleneck (DCN multi-host, where a 1000× wire saving "
           "dwarfs the compute overhead).  Use "
           "`DistributedStrategy.dgc` for DCN topologies and leave it "
           "off inside a pod.",
           ""]
    out_path = os.path.join(os.path.dirname(__file__), "dgc_crossover.md")
    with open(out_path, "w") as f:
        f.write("\n".join(md))
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
