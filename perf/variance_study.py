#!/usr/bin/env python
"""Run-to-run variance study (round-4 verdict item 4).

Measures N repetitions of (a) the two noisy bench configs (longseq
flash, widedeep PS) and (b) the op_bench suite, on the attached device.
Writes:
  * perf/variance_study.md       — mean/std/CV table
  * tools/op_bench_thresholds.json — per-op gate thresholds sized as
    max(0.15, 6×CV) from the measured distribution (a planted 1.3×
    regression must fail while run-to-run jitter must pass)

Run from the repo root:  python - < perf/variance_study.py
"""
from __future__ import annotations

import io
import json
import os
import sys
import time
from contextlib import redirect_stdout

import numpy as np

sys.path.insert(0, os.getcwd())

N = 5


def capture_bench(fn, metric):
    """Run a bench.py function, harvest one metric value from its JSON
    lines."""
    import bench
    buf = io.StringIO()
    with redirect_stdout(buf):
        fn(True)
    for line in buf.getvalue().splitlines():
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == metric:
            return rec["value"]
    raise RuntimeError(f"metric {metric} not emitted; got:\n{buf.getvalue()}")


def main():
    import bench
    from tools import op_bench

    out = {"bench": {}, "ops": {}}

    # --ops-only: keep the saved bench-leg values (the slow 100M-table
    # runs) and re-measure only the op distribution — used after an
    # estimator change in op_bench.run_one
    ops_only = "--ops-only" in sys.argv
    if ops_only:
        with open("perf/variance_raw.json") as f:
            out["bench"] = json.load(f)["bench"]

    import gc
    raw_path = "perf/variance_raw.json"

    def checkpoint():
        # crash insurance: a wedged tunnel or host OOM mid-study must not
        # lose the completed measurements
        with open(raw_path, "w") as f:
            json.dump(out, f, indent=1)

    for fn, metric in ([] if ops_only else [
        (bench.bench_longseq_flash,
         "gpt_longseq8k_flashattn_train_tokens_per_sec"),
        (lambda acc: bench.bench_widedeep_ps(acc, extra_legs=False),
         "widedeep_ps_host_table_100M_examples_per_sec"),
    ]):
        vals = []
        for i in range(N):
            v = capture_bench(fn, metric)
            vals.append(v)
            print(f"{metric} run {i+1}/{N}: {v:.1f}", flush=True)
            # the PS leg builds a ~26 GB host table per run — reclaim it
            # before the next build, not at interpreter exit
            gc.collect()
            out["bench"][metric] = vals
            checkpoint()

    # one unrecorded pass eats the per-op compile (the first measured
    # pass otherwise carries a 2-4x compile tail into the distribution)
    for cfg in op_bench.BUILTIN_SUITE:
        op_bench.run_one(cfg, iters=4, repeats=1)
    print("op suite warm pass done", flush=True)
    for i in range(N):
        for cfg in op_bench.BUILTIN_SUITE:
            r = op_bench.run_one(cfg, iters=10)
            out["ops"].setdefault(r["name"], []).append(r["ms"])
        print(f"op suite pass {i+1}/{N} done", flush=True)
        checkpoint()

    # -- write markdown ----------------------------------------------------
    lines = ["# Run-to-run variance study", "",
             f"N = {N} repetitions per config, one v5e chip via the axon "
             "tunnel, device-fetch fenced.  Op rows use the same estimator "
             "as the CI gate: a two-length jitted-scan difference "
             "(device-time per iteration; the tunnel RTT cancels in the "
             "difference), min over 3 dispatches, after one unrecorded "
             "compile-warm pass.", "",
             "| metric | mean | std | CV |", "|---|---|---|---|"]
    for metric, vals in out["bench"].items():
        a = np.asarray(vals)
        lines.append(f"| {metric} | {a.mean():.1f} | {a.std(ddof=1):.1f} "
                     f"| {a.std(ddof=1)/a.mean()*100:.1f}% |")
    thresholds = {}
    for name, vals in out["ops"].items():
        a = np.asarray(vals)
        cv = float(a.std(ddof=1) / a.mean())
        thresholds[name] = round(max(0.15, 6 * cv), 3)
        lines.append(f"| op:{name} (ms) | {a.mean():.3f} | "
                     f"{a.std(ddof=1):.4f} | {cv*100:.1f}% |")
    lines += [
        "", "Gate thresholds (`tools/op_bench_thresholds.json`) are sized "
        "as max(0.15, 6×CV) per op from this distribution: run-to-run "
        "jitter passes with ≥6σ headroom while a planted 1.3× regression "
        "fails every op whose threshold lands below 0.30 (verified by "
        "tests/test_op_bench_gate.py).", "",
        "Raw values:", "```json",
        json.dumps(out, indent=1), "```"]
    with open("perf/variance_study.md", "w") as f:
        f.write("\n".join(lines) + "\n")
    with open("tools/op_bench_thresholds.json", "w") as f:
        json.dump(thresholds, f, indent=1, sort_keys=True)
    print("wrote perf/variance_study.md + tools/op_bench_thresholds.json")


if __name__ == "__main__":
    main()
