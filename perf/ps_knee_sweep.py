"""PS-tier knee analysis: ONE shared 100M-row host table, per-B steps."""
import gc, time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import optimizer
from paddle_tpu.distributed.ps import DistributedEmbedding, PSTrainStep
from paddle_tpu.models import WideDeepHost

V, E, fields, dense_dim = 100_000_000, 64, 26, 13
rng = np.random.default_rng(0)
emb = DistributedEmbedding(V, E + 1, optimizer="adagrad",
                           learning_rate=0.05, mode="async")
model = WideDeepHost(embedding_dim=E, num_fields=fields,
                     dense_dim=dense_dim)
opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
def loss_fn(m, rows, x, y):
    return F.binary_cross_entropy_with_logits(m(rows, x), y).mean()

for B in (1024, 2048, 4096, 8192, 16384, 32768):
    step = PSTrainStep(model, loss_fn, opt, emb)
    ids = (rng.zipf(1.3, size=(B, fields)) % V).astype(np.int64)
    x = paddle.to_tensor(rng.standard_normal((B, dense_dim)).astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 2, size=(B, 1)).astype(np.float32))
    for _ in range(3):
        step(ids, x, y)
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        step(ids, x, y)
    step.flush()
    total = (time.perf_counter() - t0) / iters
    uniq = np.unique(ids.reshape(-1))
    t0 = time.perf_counter()
    for _ in range(iters):
        emb.table.pull(uniq)
    pull = (time.perf_counter() - t0) / iters
    print(f"B={B:6d} uniq={len(uniq):7d} total={total*1e3:8.1f} ms "
          f"pull={pull*1e3:7.1f} ms ({100*pull/total:4.1f}%) "
          f"eps={B/total:9.0f}", flush=True)
    gc.collect()
