#!/usr/bin/env python
"""Program lint CLI — drive the static analyzer over modules, files, or
model-zoo entries.

Reference roles: tools/check_file_diff_approvals.sh + the inference
analysis passes' IR validation, folded into one linter the CI gate and
developers share.

Usage:
    python tools/prog_lint.py paddle_tpu.vision.models --format=json
    python tools/prog_lint.py paddle_tpu/nn/layer/transformer.py
    python tools/prog_lint.py paddle_tpu               # whole package
    python tools/prog_lint.py --zoo resnet18           # jaxpr passes
    python tools/prog_lint.py --zoo all paddle_tpu.vision.models
    python tools/prog_lint.py --threads paddle_tpu     # PTA4xx passes
    python tools/prog_lint.py --collectives paddle_tpu --zoo all
    python tools/prog_lint.py --pallas paddle_tpu/ops/pallas --zoo all
    python tools/prog_lint.py --list-rules [--format=json]
    python tools/prog_lint.py --list-rules --check-docs

Targets are dotted module names or filesystem paths; packages recurse.
``--zoo`` additionally traces a vision/transformer model (tiny config,
abstract trace — no FLOPs spent) and runs the jaxpr IR passes on it.
``--threads`` switches the source front end to the concurrency pass
family (PTA401-407): all target files form ONE whole-repo lock model,
so cross-module acquisition edges and cycles are visible.
``--collectives`` arms the distributed-semantics family (PTA501-506):
zoo names resolve to the COLLECTIVES_ZOO (the parallel tier traced on
a virtual multi-device mesh — abstract, no FLOPs spent), module/dir
targets are AST-linted as usual (fault-point hygiene over the parity
probe sources rides along), and a FILE target exposing a
``collectives_report()`` hook is imported and its report used — the
committed ``tests/fixtures/replica_divergence.py`` acceptance
artifact.
``--pallas`` arms the Pallas kernel family (PTA601-606): zoo names
resolve to the PALLAS_ZOO (the hand-written kernel tier traced through
the pallas_call intercept — abstract, no FLOPs spent), module/dir
targets are AST-linted as usual, and a FILE target exposing a
``pallas_report()`` hook is imported and its report used — the
committed ``tests/fixtures/pallas_oob.py`` acceptance artifact.
``--list-rules`` prints the full rule table (id, severity, front end,
title); with ``--check-docs`` it diffs the table against the README's
rule rows and exits 1 on drift, so the docs cannot silently rot.
Exit status: 1 if any error-severity finding survives suppression
(``--strict`` also fails on warnings), 2 on bad invocation.
"""
from __future__ import annotations

import argparse
import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the model-zoo jaxpr corpus: tiny configs so abstract tracing is fast
ZOO = {
    "lenet": lambda: _zoo_model("paddle_tpu.vision.models", "LeNet",
                                dict(num_classes=10), (1, 1, 28, 28)),
    "resnet18": lambda: _zoo_model("paddle_tpu.vision.models", "resnet18",
                                   dict(num_classes=10), (1, 3, 32, 32)),
    "mobilenet_v1": lambda: _zoo_model(
        "paddle_tpu.vision.models", "mobilenet_v1",
        dict(num_classes=10, scale=0.25), (1, 3, 32, 32)),
    "mobilenet_v2": lambda: _zoo_model(
        "paddle_tpu.vision.models", "mobilenet_v2",
        dict(num_classes=10, scale=0.25), (1, 3, 32, 32)),
    "vgg11": lambda: _zoo_model("paddle_tpu.vision.models", "vgg11",
                                dict(num_classes=10), (1, 3, 224, 224)),
    "transformer_encoder": lambda: _zoo_transformer(),
    # returns a finished Report (step trace + chaos-source lint), not a
    # (model, inputs) pair — see the Report branch in main()
    "elastic_step": lambda: _zoo_elastic_step(),
    # lints the chaos-threaded PS transport sources (ps.rpc /
    # ps.pipeline fault-point hygiene) — Report, like elastic_step
    "ps_transport": lambda: _zoo_ps_transport(),
    # lints the streaming ingest plane sources (data.pipeline
    # fault-point hygiene) — Report, like elastic_step
    "ingest": lambda: _zoo_ingest(),
    # lints the perf health plane sources (health.detector fault-point
    # hygiene + the jit compile-observability hooks) — Report, like
    # elastic_step
    "health": lambda: _zoo_health(),
    # lints the ZeRO sharded-update tier (zero.collective fault-point
    # hygiene + the shared wire-quantization helpers and the dp meta
    # strategies folded onto them) — Report, like elastic_step
    "zero_step": lambda: _zoo_zero_step(),
    # traces a numerics-ARMED resilient train step (the aux reductions
    # are part of the jaxpr) and lints the model-numerics plane sources
    # (numerics.observe fault-point hygiene + the GradScaler telemetry
    # consumer) — Report, like elastic_step
    "numerics_step": lambda: _zoo_numerics_step(),
    # lints the continuous-perf observatory sources (runlog.observe
    # fault-point hygiene in the run ledger + its TrainEpochRange
    # producer hook) — Report, like elastic_step
    "runlog": lambda: _zoo_runlog(),
    # lints the cluster telemetry plane (collector.rpc fault-point
    # hygiene in the fire-and-forget pusher + the MetricsReporter push
    # mode and the launcher's endpoint plumbing) — Report, like
    # elastic_step
    "collector": lambda: _zoo_collector(),
    # lints the durable-state plane (ckpt.save / ckpt.async /
    # ckpt.verify fault-point hygiene across the checkpoint writer,
    # the generation manager, the two-slot epoch protocol, and the
    # crash-safe fs tier) — Report, like elastic_step
    "ckpt": lambda: _zoo_ckpt(),
    # lints the postmortem plane (incident.capture fault-point hygiene
    # in the bundle writer + the ring hook threaded through the
    # resilient step) — Report, like elastic_step
    "incident": lambda: _zoo_incident(),
}


def _zoo_model(module, ctor, kwargs, input_shape):
    import importlib

    import jax
    import jax.numpy as jnp
    mod = importlib.import_module(module)
    model = getattr(mod, ctor)(**kwargs)
    model.eval()
    x = jax.ShapeDtypeStruct(input_shape, jnp.float32)
    return model, (x,)


def _zoo_transformer():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.layer.transformer import (TransformerEncoder,
                                                 TransformerEncoderLayer)
    layer = TransformerEncoderLayer(d_model=64, nhead=4,
                                    dim_feedforward=128, dropout=0.0)
    model = TransformerEncoder(layer, num_layers=2)
    model.eval()
    x = jax.ShapeDtypeStruct((2, 16, 64), jnp.float32)
    return model, (x,)


def _zoo_elastic_step():
    """The elastic train step, both front ends: the jaxpr IR passes trace
    the fused TrainStep a ResilientTrainStep drives (abstract, no FLOPs),
    and the AST lint covers the chaos-threaded elastic/resilient sources
    — so PTA301/302 validate the ``elastic.lease`` /
    ``elastic.worker_hang`` / ``train.step_grads`` fault-point sites the
    elastic loop fires every step."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.framework.analysis import lint_file
    from paddle_tpu.framework.resilient import ResilientTrainStep
    from paddle_tpu.jit import TrainStep

    class _MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(6, 12)
            self.fc2 = nn.Linear(12, 3)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    def loss_fn(m, x, y):
        return paddle.nn.functional.cross_entropy(m(x), y).mean()

    paddle.seed(0)
    model = _MLP()
    opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=model.parameters())
    resilient = ResilientTrainStep(
        TrainStep(model, loss_fn, opt, donate=False))
    report = resilient.step.analyze(
        np.zeros((4, 6), np.float32), np.zeros((4,), np.int64))
    for rel in (os.path.join("paddle_tpu", "distributed", "elastic.py"),
                os.path.join("paddle_tpu", "framework", "resilient.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_ps_transport():
    """AST-lint the PS transport tier — the sources threading the
    ``ps.rpc`` and ``ps.pipeline`` chaos fault points (client retry
    loop, prefetch pipeline, wire quantization helpers) — so PTA301/302
    validate every transport fault-point site against the registry and
    its retry-ownership pragmas."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "distributed", "ps",
                             "__init__.py"),
                os.path.join("paddle_tpu", "distributed", "ps",
                             "service.py"),
                os.path.join("paddle_tpu", "distributed", "ps",
                             "device_table.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_ingest():
    """AST-lint the streaming ingest plane — the sources threading the
    ``data.pipeline`` chaos fault point (IngestPipeline background
    tasks, the worker-collate loader, the decoded-sample cache) — so
    PTA301/302 validate the new fault-point site against the registry
    and its retry-ownership pragma."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "io", "pipeline.py"),
                os.path.join("paddle_tpu", "io", "__init__.py"),
                os.path.join("paddle_tpu", "io", "_worker.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_health():
    """AST-lint the perf health plane — framework/health.py (which
    threads the ``health.detector`` chaos fault point through every
    observation) plus the jit tier carrying the compile-observability
    hooks — so PTA301/302 validate the new fault-point site against
    the registry and its swallow-and-count guard."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "framework", "health.py"),
                os.path.join("paddle_tpu", "jit", "__init__.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_zero_step():
    """AST-lint the sharded weight-update tier — ``parallel/zero.py``
    (which threads the ``zero.collective`` chaos fault point through the
    dispatch head), the shared wire-quantization helpers both the PS
    transport and the collective legs encode with, and the dp meta
    strategies folded onto them — so PTA301/302 validate the new
    fault-point site against the registry and its bounded-retry
    ownership pragma."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "parallel", "zero.py"),
                os.path.join("paddle_tpu", "parallel", "dp_meta.py"),
                os.path.join("paddle_tpu", "distributed", "wire.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_numerics_step():
    """The model-numerics plane, both front ends: the jaxpr IR passes
    trace the fused TrainStep WITH the in-jit numerics aux armed
    (FLAGS_numerics — the aux reductions are real equations in the
    traced step, so dead-code/cost passes see them), and the AST lint
    covers the sources threading the ``numerics.observe`` fault point
    (framework/numerics.py publish) plus its consumers — resilient's
    provenance path and the GradScaler scale telemetry."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.framework.analysis import lint_file
    from paddle_tpu.framework.flags import get_flags, set_flags
    from paddle_tpu.framework.resilient import ResilientTrainStep
    from paddle_tpu.jit import TrainStep

    class _MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(6, 12)
            self.fc2 = nn.Linear(12, 3)

        def forward(self, x):
            return self.fc2(paddle.nn.functional.relu(self.fc1(x)))

    def loss_fn(m, x, y):
        return paddle.nn.functional.cross_entropy(m(x), y).mean()

    paddle.seed(0)
    model = _MLP()
    opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=model.parameters())
    resilient = ResilientTrainStep(
        TrainStep(model, loss_fn, opt, donate=False))
    saved = get_flags("numerics")
    set_flags({"numerics": True})
    try:
        report = resilient.step.analyze(
            np.zeros((4, 6), np.float32), np.zeros((4,), np.int64))
    finally:
        set_flags(saved)
    for rel in (os.path.join("paddle_tpu", "framework", "numerics.py"),
                os.path.join("paddle_tpu", "framework", "resilient.py"),
                os.path.join("paddle_tpu", "amp", "__init__.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_runlog():
    """AST-lint the continuous-perf observatory — the run ledger
    (framework/runlog.py, which threads the ``runlog.observe`` chaos
    fault point through every append) plus its in-framework producer
    hook (auto_checkpoint's TrainEpochRange) — so PTA301/302 validate
    the new fault-point site against the registry and its
    swallow-and-count guard."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "framework", "runlog.py"),
                os.path.join("paddle_tpu", "framework",
                             "auto_checkpoint.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_collector():
    """AST-lint the cluster telemetry plane — ``framework/collector.py``
    (which threads the ``collector.rpc`` chaos fault point through
    every fire-and-forget push attempt), the ``MetricsReporter`` push
    mode in ``framework/observability.py``, and the launcher's
    collector-endpoint env plumbing — so PTA301/302 validate the new
    fault-point site against the registry and its drop-and-count
    ownership pragma."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "framework", "collector.py"),
                os.path.join("paddle_tpu", "framework",
                             "observability.py"),
                os.path.join("paddle_tpu", "distributed", "launch.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_ckpt():
    """AST-lint the durable-state plane — ``distributed/checkpoint.py``
    (which threads the ``ckpt.save`` / ``ckpt.async`` / ``ckpt.verify``
    chaos fault points through the shard writer, the async dispatch,
    and the integrity verifier), the generation manager
    (``distributed/durable.py``), the two-slot epoch protocol
    (``framework/auto_checkpoint.py``), and the crash-safe fs tier
    (``fleet/utils/fs.py``) — so PTA301/302 validate every new
    fault-point site against the registry and its recovery-ownership
    pragma."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "distributed", "checkpoint.py"),
                os.path.join("paddle_tpu", "distributed", "durable.py"),
                os.path.join("paddle_tpu", "framework",
                             "auto_checkpoint.py"),
                os.path.join("paddle_tpu", "distributed", "fleet",
                             "utils", "fs.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


def _zoo_incident():
    """AST-lint the postmortem plane — ``framework/incident.py`` (which
    threads the ``incident.capture`` chaos fault point through bundle
    assembly under the swallow-and-count guard) plus the ring hook's
    host (``framework/resilient.py``, whose ``train.step_grads`` site
    carries the recovery-ownership pragma) — so PTA301/302 validate the
    new fault-point site against the registry."""
    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    for rel in (os.path.join("paddle_tpu", "framework", "incident.py"),
                os.path.join("paddle_tpu", "framework", "resilient.py")):
        sub = lint_file(os.path.join(REPO, rel))
        sub.files_seen = [rel]
        for d in sub.diagnostics:
            d.file = rel
        report.extend(sub)
    return report


# ---------------------------------------------------------------------------
# --collectives zoo: the distributed tier traced on a virtual mesh and
# run through the PTA5xx passes (plus the full PTA1xx stack).  Every
# entry returns a finished Report and must stay clean at zero errors
# AND zero warnings — the regression guard for the sharded-execution
# paths' distributed semantics.
# ---------------------------------------------------------------------------


def _virtual_devices(n: int = 8):
    """Force a CPU virtual device mesh BEFORE jax initializes (the
    op_bench --zero-collectives idiom); no-op once jax is up."""
    import sys as _sys
    if "jax" not in _sys.modules:
        xf = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xf:
            os.environ["XLA_FLAGS"] = (
                xf + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def _require_devices(n: int, who: str):
    import jax
    if len(jax.devices()) < n:
        raise SystemExit(
            f"prog_lint: {who} needs >= {n} devices for its virtual "
            "mesh (CPU hosts get one automatically unless jax was "
            "already initialized single-device)")


def _czoo_zero_step():
    """Trace the ZeRO sharded-update step (dp=2, default wire, global-
    norm clip armed so the clip-psum idiom is in the jaxpr) and run the
    full jaxpr+PTA5xx stack via its analyze() hook."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.zero import ShardedUpdateTrainStep
    _require_devices(2, "zoo:zero_step")
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = optimizer.Momentum(
        learning_rate=0.01, momentum=0.9,
        parameters=model.parameters(),
        grad_clip=nn.ClipGradByGlobalNorm(1.0))

    def loss_fn(m, x, y):
        return ((m(x) - y) ** 2).mean()

    step = ShardedUpdateTrainStep(model, loss_fn, opt, mesh=mesh)
    return step.analyze(
        jax.ShapeDtypeStruct((8, 8), jnp.float32),
        jax.ShapeDtypeStruct((8, 4), jnp.float32))


def _czoo_sharded_step():
    """Trace the pjit hybrid step (dp=2 x sharding=2, stage-2 ZeRO
    layout) through its inherited analyze() — the pjit-region walk of
    the PTA5xx passes (XLA owns the collectives there; the passes must
    stay silent)."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.parallel import ShardedTrainStep, make_mesh
    _require_devices(4, "zoo:sharded_step")
    mesh = make_mesh({"dp": 2, "sharding": 2},
                     devices=jax.devices()[:4])
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                             parameters=model.parameters())

    def loss_fn(m, x, y):
        return ((m(x) - y) ** 2).mean()

    step = ShardedTrainStep(model, loss_fn, opt, mesh=mesh,
                            sharding_stage=2)
    return step.analyze(np.zeros((8, 8), np.float32),
                        np.zeros((8, 4), np.float32))


def _czoo_tp_layers():
    """Trace a column->row tensor-parallel block (mp=2) — the
    sharding-constraint path the tp layers ride; the PTA5xx passes walk
    the constrained pjit program and must stay silent."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.tp_layers import (ColumnParallelLinear,
                                                  RowParallelLinear)
    from paddle_tpu.framework.analysis import analyze_model
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.mesh import set_mesh
    _require_devices(2, "zoo:tp_layers")
    paddle.seed(0)
    from paddle_tpu.parallel import mesh as mesh_mod
    prev = mesh_mod._global_mesh
    set_mesh(make_mesh({"mp": 2}, devices=jax.devices()[:2]))

    class _Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = ColumnParallelLinear(8, 16, gather_output=False)
            self.row = RowParallelLinear(16, 4, input_is_parallel=True)

        def forward(self, x):
            return self.row(paddle.nn.functional.relu(self.col(x)))

    try:
        model = _Block()
        model.eval()
        return analyze_model(
            model, jax.ShapeDtypeStruct((2, 8), jnp.float32),
            name="zoo:tp_layers")
    finally:
        set_mesh(prev)


def _czoo_ring_attention():
    """Trace ring attention on an sp=2 mesh — the ppermute-in-scan
    manual region (sequence parallelism); the PTA5xx passes must
    accept the rotating-chunk schedule (outputs stay sp-sharded)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.analysis import analyze_callable
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention
    _require_devices(2, "zoo:ring_attention")
    mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])

    def attn(q, k, v):
        return ring_attention(q, k, v, causal=True, mesh=mesh)

    shape = (2, 8, 2, 4)                  # (B, S, H, D), S sharded on sp
    return analyze_callable(
        attn, *(jax.ShapeDtypeStruct(shape, jnp.float32),) * 3,
        name="zoo:ring_attention")


def _czoo_ring_collectives():
    """Trace the fused quantized ring collectives (parallel/ring.py) on
    a dp=4 mesh — the quantize-inside-a-ppermute-scan idiom.  The ring
    RS carries an encoded partial with an f32 accumulator and the ring
    AG assembles every seat's chunk via a complete-cycle scan; PTA504
    must accept the decode-add-reencode hop and PTA501 must recognize
    the complete ring as a gather (zero findings)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.framework.analysis import analyze_callable
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.mesh import shard_map_compat
    from paddle_tpu.parallel.ring import (ring_all_gather,
                                          ring_reduce_scatter)
    _require_devices(4, "zoo:ring_collectives")
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])

    def allreduce(g):
        def body(gflat):
            s = ring_reduce_scatter(gflat, "dp", axis_size=4, chunk=8,
                                    wire="int8") / 4
            return ring_all_gather(s, "dp", axis_size=4, chunk=8,
                                   wire="int4")
        return shard_map_compat(body, mesh, P(), P())(g)

    return analyze_callable(
        allreduce, jax.ShapeDtypeStruct((128,), jnp.float32),
        name="zoo:ring_collectives")


COLLECTIVES_ZOO = {
    "zero_step": _czoo_zero_step,
    "sharded_step": _czoo_sharded_step,
    "tp_layers": _czoo_tp_layers,
    "ring_attention": _czoo_ring_attention,
    "ring_collectives": _czoo_ring_collectives,
}


# ---------------------------------------------------------------------------
# --pallas zoo: the hand-written kernel tier.  Each entry traces its
# public entry point through trace_kernels (the pallas_call intercept
# under eval_shape — abstract, no FLOPs spent) and runs the PTA6xx
# passes on every captured kernel model.  Every entry returns a
# finished Report and must stay clean at zero errors AND zero warnings
# — the regression guard for the kernels' tiling/masking/precision
# invariants.
# ---------------------------------------------------------------------------


def _pzoo_flash_attention():
    """Trace the flash-attention fwd+bwd kernels at a NON-divisible
    causal shape (sq=sk=1300: tail blocks on both grid axes) — the
    configuration the PTA601/PTA604 tail-mask passes exist for — with
    grads so the dq/dkv kernels are captured too."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.analysis import analyze_kernels
    from paddle_tpu.ops.pallas import flash_attention as fa

    def loss(q, k, v):
        return fa.flash_attention(q, k, v, causal=True).astype(
            jnp.float32).sum()

    sds = jax.ShapeDtypeStruct((1, 1300, 2, 64), jnp.bfloat16)
    return analyze_kernels(jax.grad(loss, argnums=(0, 1, 2)),
                           sds, sds, sds, name="zoo:flash")


def _pzoo_fused_adam():
    """Trace the fused Adam elementwise kernel on an odd (non-tile-
    aligned) flat parameter size — the pad/reshape path."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.analysis import analyze_kernels
    from paddle_tpu.ops.pallas.fused_adam import fused_adam_update

    sds = jax.ShapeDtypeStruct((100003,), jnp.float32)
    return analyze_kernels(
        lambda p, g, m, v: fused_adam_update(
            p, g, m, v, lr_t=1e-3, beta1=0.9, beta2=0.999, eps=1e-8),
        sds, sds, sds, sds, name="zoo:fused_adam")


def _pzoo_fused_ce():
    """Trace the fused linear-cross-entropy kernels (logz + dh/dw) at a
    non-divisible token count (n=300) with grads — the padded-tail
    configuration its PTA601 fix covers."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.analysis import analyze_kernels
    from paddle_tpu.ops.pallas.fused_ce import fused_linear_cross_entropy

    def loss(h, w, labels):
        return fused_linear_cross_entropy(h, w, labels).sum()

    h = jax.ShapeDtypeStruct((300, 128), jnp.float32)
    w = jax.ShapeDtypeStruct((1000, 128), jnp.float32)
    lab = jax.ShapeDtypeStruct((300,), jnp.int32)
    return analyze_kernels(jax.grad(loss, argnums=(0, 1)), h, w, lab,
                           name="zoo:fused_ce")


def _pzoo_ring_attention():
    """Trace ring attention on an sp=2 virtual mesh.  The ppermute
    schedule is currently pure jnp — the trace captures zero
    pallas_call models and the report is empty by construction — but
    the entry pins the coverage surface: the planned ragged
    paged-attention / fused ring-collective kernels (ROADMAP) land
    inside this trace the day they exist."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.analysis import analyze_kernels
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.ring_attention import ring_attention
    _require_devices(2, "zoo:ring_attention")
    mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])

    def attn(q, k, v):
        return ring_attention(q, k, v, causal=True, mesh=mesh)

    sds = jax.ShapeDtypeStruct((2, 8, 2, 4), jnp.float32)
    return analyze_kernels(attn, sds, sds, sds,
                           name="zoo:ring_attention")


def _pzoo_ring_quant():
    """Trace the fused-ring row quantizer at a non-row-block-aligned
    row count (r=1000: padded tail block) for both quantized wires —
    the pad/slice path the ring's codec leg rides."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.framework.analysis import analyze_kernels
    from paddle_tpu.ops.pallas.ring_quant import ring_quant_rows

    sds = jax.ShapeDtypeStruct((1000, 256), jnp.float32)
    return analyze_kernels(
        lambda x: (ring_quant_rows(x, "int8", force=True)
                   + ring_quant_rows(x, "int4", force=True)),
        sds, name="zoo:ring_quant")


PALLAS_ZOO = {
    "flash_attention": _pzoo_flash_attention,
    "fused_adam": _pzoo_fused_adam,
    "fused_ce": _pzoo_fused_ce,
    "ring_attention": _pzoo_ring_attention,
    "ring_quant": _pzoo_ring_quant,
}


def _file_hook_report(path: str, hook_name: str):
    """Import a file target and return its ``<hook_name>()`` Report, or
    None when the file declares no hook (it is then AST-linted like any
    other target)."""
    import importlib.util
    name = "_prog_lint_hook_" + \
        os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    hook = getattr(mod, hook_name, None)
    return hook() if callable(hook) else None


def _collectives_file_report(path: str):
    """Import a file target and return its ``collectives_report()``
    Report, or None when the file declares no hook (it is then
    AST-linted like any other target)."""
    return _file_hook_report(path, "collectives_report")


def resolve_target(target: str):
    """A dotted module name or path -> list of .py files to lint."""
    if os.path.exists(target):
        if os.path.isdir(target):
            return sorted(
                os.path.join(dp, f)
                for dp, _, fs in os.walk(target) for f in fs
                if f.endswith(".py"))
        return [target]
    try:
        spec = importlib.util.find_spec(target)
    except (ImportError, ModuleNotFoundError):
        spec = None
    if spec is None or spec.origin is None:
        raise SystemExit(f"prog_lint: cannot resolve target {target!r} "
                         "(not a path, not an importable module)")
    origin = spec.origin
    if os.path.basename(origin) == "__init__.py":
        return resolve_target(os.path.dirname(origin))
    return [origin]


def list_rules(fmt: str = "text") -> str:
    """The full registered rule table (``--list-rules``)."""
    import json as _json

    from paddle_tpu.framework.analysis import RULES
    rows = [{"id": r.id, "severity": str(r.severity),
             "frontend": r.frontend, "title": r.title}
            for r in sorted(RULES.values(), key=lambda r: r.id)]
    if fmt == "json":
        return _json.dumps({"rules": rows}, indent=1)
    w = max(len(r["title"]) for r in rows)
    lines = [f"{'id':<8} {'severity':<8} {'frontend':<8} title",
             "-" * (28 + w)]
    for r in rows:
        lines.append(f"{r['id']:<8} {r['severity']:<8} "
                     f"{r['frontend']:<8} {r['title']}")
    return "\n".join(lines)


def check_docs(readme_path: str = None) -> list:
    """Diff the registered rule table against the README's rule rows
    (``| `PTAnnn` | frontend | severity | ... |``).  Returns a list of
    drift messages — empty when the docs match the registry."""
    import re

    from paddle_tpu.framework.analysis import RULES
    readme_path = readme_path or os.path.join(REPO, "README.md")
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    row_re = re.compile(
        r"^\|\s*`(PTA\d+)`\s*\|\s*([^|]+?)\s*\|\s*([^|]+?)\s*\|",
        re.MULTILINE)
    doc_rows = {m.group(1): (m.group(2).strip(), m.group(3).strip())
                for m in row_re.finditer(text)}
    problems = []
    fe_alias = {"ast": "ast", "chaos": "ast", "jaxpr": "jaxpr",
                "threads": "threads", "collective": "collective"}
    for rid, info in sorted(RULES.items()):
        if rid not in doc_rows:
            problems.append(f"{rid}: registered but missing from the "
                            f"README rule table")
            continue
        fe_doc, sev_doc = doc_rows[rid]
        want_fe = fe_alias.get(info.frontend, info.frontend)
        if fe_doc.lower() not in (want_fe, info.frontend):
            problems.append(f"{rid}: README front end {fe_doc!r} != "
                            f"registry {info.frontend!r}")
        sev_short = {"warning": "warn", "error": "error",
                     "info": "info"}[str(info.severity)]
        if sev_short not in sev_doc.lower():
            problems.append(f"{rid}: README severity {sev_doc!r} does "
                            f"not mention registry default "
                            f"{info.severity}")
    for rid in sorted(doc_rows):
        if rid not in RULES:
            problems.append(f"{rid}: documented in README but not "
                            "registered in any front end")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="prog_lint.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("targets", nargs="*",
                    help="dotted module names or file/dir paths")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--zoo", action="append", default=[],
                    metavar="ENTRY",
                    help="run the jaxpr IR passes on a model-zoo entry "
                         f"({', '.join(sorted(ZOO))}, or 'all')")
    ap.add_argument("--threads", action="store_true",
                    help="run the concurrency pass family (PTA401-407) "
                         "over the targets as one whole-repo lock "
                         "model, instead of the jit-safety lint")
    ap.add_argument("--collectives", action="store_true",
                    help="arm the distributed-semantics pass family "
                         "(PTA501-506): zoo entries resolve to the "
                         "traced parallel tier "
                         f"({', '.join(sorted(COLLECTIVES_ZOO))}), "
                         "file targets with a collectives_report() "
                         "hook are imported, other targets AST-lint "
                         "as usual")
    ap.add_argument("--pallas", action="store_true",
                    help="arm the Pallas kernel pass family "
                         "(PTA601-606): zoo entries resolve to the "
                         "traced kernel tier "
                         f"({', '.join(sorted(PALLAS_ZOO))}), file "
                         "targets with a pallas_report() hook are "
                         "imported, other targets AST-lint as usual")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the registered rule table and exit")
    ap.add_argument("--check-docs", action="store_true",
                    help="with --list-rules: diff the rule table "
                         "against the README rows; exit 1 on drift")
    ap.add_argument("--disable", default="",
                    help="comma-separated rule IDs to drop (jaxpr rules "
                         "have no source line for inline pragmas)")
    ap.add_argument("--min-severity", default="info",
                    choices=("info", "warning", "error"),
                    help="report floor (exit status always keys off "
                         "errors; --strict adds warnings)")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings too")
    ap.add_argument("--no-cost", action="store_true",
                    help="skip the PTA106 cost report (quieter json)")
    a = ap.parse_args(argv)
    if sum((a.threads, a.collectives, a.pallas)) > 1:
        ap.error("--threads, --collectives and --pallas are distinct "
                 "front ends; run them as separate invocations")
    if a.collectives or a.pallas:
        # these zoos trace dp/mp/sharding/sp meshes: make the virtual
        # CPU devices exist before jax initializes
        _virtual_devices(8)
    if a.list_rules:
        print(list_rules(a.format))
        if a.check_docs:
            problems = check_docs()
            if problems:
                for p in problems:
                    print(f"DOC DRIFT: {p}", file=sys.stderr)
                return 1
            from paddle_tpu.framework.analysis import RULES
            print(f"rule table matches README ({len(RULES)} rules)")
        return 0
    if not a.targets and not a.zoo:
        ap.error("nothing to lint: pass a target module/path or --zoo")
    disable = [r.strip() for r in a.disable.split(",") if r.strip()]

    from paddle_tpu.framework.analysis import Report, lint_file
    report = Report()
    if a.threads:
        from paddle_tpu.framework.analysis import analyze_files
        paths = [p for target in a.targets
                 for p in resolve_target(target)]
        sub = analyze_files(paths, disable=disable)
        sub.files_seen = [os.path.relpath(p, REPO)
                          if p.startswith(REPO) else p for p in paths]
        for d in sub.diagnostics:
            if d.file and d.file.startswith(REPO):
                d.file = os.path.relpath(d.file, REPO)
        report.extend(sub)
    else:
        for target in a.targets:
            for path in resolve_target(target):
                rel = os.path.relpath(path, REPO) \
                    if path.startswith(REPO) else path
                if (a.collectives or a.pallas) and \
                        os.path.isfile(target) and path == target:
                    # a single-file target may carry the traced-fixture
                    # hook (collectives_report / pallas_report) — the
                    # committed divergence fixtures' static halves
                    hooked = _file_hook_report(
                        path, "pallas_report" if a.pallas
                        else "collectives_report")
                    if hooked is not None:
                        hooked.files_seen = [rel]
                        report.extend(hooked)
                        continue
                sub = lint_file(path, disable=disable)
                sub.files_seen = [rel]
                for d in sub.diagnostics:
                    d.file = rel
                report.extend(sub)

    zoo_map = PALLAS_ZOO if a.pallas else \
        COLLECTIVES_ZOO if a.collectives else ZOO
    zoo = a.zoo
    if "all" in zoo:
        zoo = sorted(zoo_map)
    for entry in zoo:
        if entry not in zoo_map:
            raise SystemExit(f"prog_lint: unknown zoo entry {entry!r} "
                             f"(have: {', '.join(sorted(zoo_map))})")
        from paddle_tpu.framework.analysis import Report as _Report
        from paddle_tpu.framework.analysis import analyze_model
        out = zoo_map[entry]()
        if isinstance(out, _Report):     # pre-built report (elastic_step)
            if a.no_cost:                # honor --no-cost like the
                out = out.filter(disable=["PTA106"])  # analyze_model path
            report.extend(out)
            continue
        model, inputs = out
        report.extend(analyze_model(
            model, *inputs, name=f"zoo:{entry}", disable=disable,
            with_cost=not a.no_cost))

    shown = report.filter(min_severity=a.min_severity, disable=disable)
    if a.format == "json":
        print(shown.to_json())
    else:
        print(shown.to_text())
    # exit status is computed over the FULL report (floor only hides
    # output) so --min-severity=info can never mask a failing error
    return report.filter(disable=disable).exit_code(strict=a.strict)


if __name__ == "__main__":
    sys.exit(main())
