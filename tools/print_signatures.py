#!/usr/bin/env python
"""API signature freeze — the compat surface as a checked-in spec.

Reference roles: tools/print_signatures.py (walk a module tree, print
every public callable's argspec in sorted order) + paddle/fluid/API.spec
(the frozen file a CI diff guards).  An API change here must come with a
deliberate regeneration:

    python tools/print_signatures.py --update        # rewrite API.spec
    python tools/print_signatures.py --check         # exit 1 on drift

``tests/test_api_spec.py`` runs the check in the suite, so signature
drift — a renamed kwarg, a dropped default, a vanished fluid alias —
fails tests instead of silently breaking user code.
"""
from __future__ import annotations

import argparse
import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# signature walking never needs the accelerator; pin CPU before the
# paddle_tpu import so the tool runs even while a trainer holds the chip
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

SPEC_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "API.spec")

# The modules whose public names form the frozen surface.  Kept explicit —
# a new module must be added here (and the spec regenerated) to be guarded.
MODULES = [
    "paddle_tpu",
    "paddle_tpu.nn",
    "paddle_tpu.nn.functional",
    "paddle_tpu.nn.initializer",
    "paddle_tpu.optimizer",
    "paddle_tpu.optimizer.lr",
    "paddle_tpu.tensor",
    "paddle_tpu.io",
    "paddle_tpu.io.pipeline",
    "paddle_tpu.amp",
    "paddle_tpu.autograd",
    "paddle_tpu.jit",
    "paddle_tpu.static",
    "paddle_tpu.static.nn",
    "paddle_tpu.metric",
    "paddle_tpu.distribution",
    "paddle_tpu.distributed",
    "paddle_tpu.distributed.elastic",
    "paddle_tpu.distributed.checkpoint",
    "paddle_tpu.distributed.durable",
    "paddle_tpu.distributed.wire",
    "paddle_tpu.distributed.ps",
    "paddle_tpu.distributed.ps.service",
    "paddle_tpu.distributed.fleet",
    "paddle_tpu.vision",
    "paddle_tpu.vision.models",
    "paddle_tpu.vision.transforms",
    "paddle_tpu.vision.ops",
    "paddle_tpu.text",
    "paddle_tpu.hapi",
    "paddle_tpu.inference",
    "paddle_tpu.quantization",
    "paddle_tpu.profiler",
    "paddle_tpu.onnx",
    "paddle_tpu.regularizer",
    "paddle_tpu.parallel.zero",
    "paddle_tpu.parallel.ring",
    "paddle_tpu.parallel.dp_meta",
    "paddle_tpu.framework.flags",
    "paddle_tpu.framework.crypto",
    "paddle_tpu.framework.monitor",
    "paddle_tpu.framework.observability",
    "paddle_tpu.framework.blame",
    "paddle_tpu.framework.health",
    "paddle_tpu.framework.numerics",
    "paddle_tpu.framework.runlog",
    "paddle_tpu.framework.collector",
    "paddle_tpu.framework.autopilot",
    "paddle_tpu.framework.incident",
    "paddle_tpu.framework.locks",
    "paddle_tpu.framework.analysis.concurrency",
    "paddle_tpu.framework.analysis.collectives",
    "paddle_tpu.framework.analysis.pallas_kernels",
    "paddle_tpu.ops.pallas.verify",
    "paddle_tpu.parallel.parity",
    "paddle_tpu.distributed.fleet.metrics",
    "paddle_tpu.distributed.fleet.utils.fs",
    "paddle_tpu.utils.cpp_extension",
    "paddle_tpu.reader",
    "paddle_tpu.device",
    "paddle_tpu.version",
    "paddle_tpu.sysconfig",
    "paddle_tpu.incubate",
    "paddle_tpu.dataset",
    "paddle_tpu.dataset.common",
    "paddle_tpu.dataset.mnist",
    "paddle_tpu.fluid",
    "paddle_tpu.fluid.layers",
    "paddle_tpu.fluid.dygraph",
    "paddle_tpu.fluid.initializer",
    "paddle_tpu.fluid.io",
    "paddle_tpu.fluid.optimizer",
    "paddle_tpu.incubate.optimizer",
    "paddle_tpu.utils",
]


def _sig_of(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(<unresolvable>)"


def _collect() -> dict:
    entries = {}
    for modname in MODULES:
        mod = importlib.import_module(modname)
        public = getattr(mod, "__all__", None)
        if public is None:
            public = [n for n in dir(mod) if not n.startswith("_")]
        for name in public:
            obj = getattr(mod, name, None)
            if obj is None:
                entries[f"{modname}.{name}"] = "MISSING-FROM-MODULE"
                continue
            if inspect.ismodule(obj):
                continue
            path = f"{modname}.{name}"
            if inspect.isclass(obj):
                entries[path] = "class" + _sig_of(obj)
                for mname, meth in sorted(vars(obj).items()):
                    if mname.startswith("_"):
                        continue
                    if callable(meth) or isinstance(
                            meth, (staticmethod, classmethod)):
                        fn = meth.__func__ if isinstance(
                            meth, (staticmethod, classmethod)) else meth
                        if callable(fn):
                            entries[f"{path}.{mname}"] = _sig_of(fn)
            elif callable(obj):
                entries[path] = _sig_of(obj)
            else:
                entries[path] = f"value:{type(obj).__name__}"
    return entries


def render() -> str:
    entries = _collect()
    lines = [f"{k} {v}" for k, v in sorted(entries.items())]
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--update", action="store_true",
                    help="regenerate API.spec")
    ap.add_argument("--check", action="store_true",
                    help="diff current surface against API.spec")
    a = ap.parse_args(argv)
    text = render()
    if a.update:
        with open(SPEC_PATH, "w") as f:
            f.write(text)
        print(f"wrote {SPEC_PATH} ({len(text.splitlines())} entries)")
        return 0
    if a.check:
        if not os.path.exists(SPEC_PATH):
            print("API.spec missing — run --update first", file=sys.stderr)
            return 1
        with open(SPEC_PATH) as f:
            frozen = f.read()
        if frozen == text:
            return 0
        import difflib
        diff = difflib.unified_diff(
            frozen.splitlines(), text.splitlines(),
            fromfile="API.spec (frozen)", tofile="current surface",
            lineterm="")
        for line in list(diff)[:80]:
            print(line, file=sys.stderr)
        print("\nAPI surface drifted from API.spec. If intentional, run\n"
              "  python tools/print_signatures.py --update",
              file=sys.stderr)
        return 1
    sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
