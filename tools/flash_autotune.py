#!/usr/bin/env python
"""Measure flash-attention block sizes on the attached TPU and persist
the winners into paddle_tpu/ops/pallas/flash_blocks.json.

    python tools/flash_autotune.py                  # bench/model configs
    python tools/flash_autotune.py --sq 4096 --sk 4096 --d 128 --causal

The shipped json is the measured cache the kernels consult at trace
time; re-run this on new hardware generations.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (sq, sk, d, dtype, causal, biased) — the bench + model-zoo kernel shapes
DEFAULT_CONFIGS = [
    (1024, 1024, 64, "bfloat16", True, False),    # GPT-2 345M
    (2048, 2048, 128, "bfloat16", True, False),   # longseq ref leg
    (8192, 8192, 128, "bfloat16", True, False),   # longseq 8k leg
    (2048, 2048, 64, "bfloat16", False, True),    # masked BERT-class
    (8192, 8192, 128, "bfloat16", True, True),    # packed longseq
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sq", type=int)
    ap.add_argument("--sk", type=int)
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--causal", action="store_true")
    ap.add_argument("--biased", action="store_true")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--split", action="store_true",
                    help="tune fwd and bwd block sizes independently")
    ap.add_argument("--no-verify", action="store_true",
                    help="skip the differential oracle pre-timing gate "
                         "(candidates are then recorded unstamped)")
    a = ap.parse_args(argv)

    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.ops.pallas import autotune
    from paddle_tpu.ops.pallas.flash_attention import _backend_is_tpu
    if not _backend_is_tpu():
        print("no TPU attached — autotune must run on real hardware",
              file=sys.stderr)
        return 1
    if not a.no_verify:
        # every candidate passes the interpret-vs-compiled-vs-reference
        # oracle before it is timed; winners are stamped verified: true
        set_flags({"pallas_verify": True})

    configs = [(a.sq, a.sk, a.d, a.dtype, a.causal, a.biased)] \
        if a.sq else DEFAULT_CONFIGS
    for sq, sk, d, dt, causal, biased in configs:
        print(f"config sq={sq} sk={sk} d={d} {dt} "
              f"causal={causal} biased={biased}")
        rejected = {}
        if a.split:
            out = autotune.measure_split(sq, sk, d, dt, causal, biased,
                                         iters=a.iters, verbose=True,
                                         rejected=rejected)
            if out is None:
                print("  no viable candidate")
            else:
                fwd, bwd = out
                print(f"  -> fwd {fwd[0]}" +
                      (f", bwd {bwd[0]}" if bwd else ""))
        else:
            out = autotune.measure(sq, sk, d, dt, causal, biased,
                                   iters=a.iters, verbose=True,
                                   rejected=rejected)
            if out is None:
                print("  no viable candidate")
            else:
                best, _ = out
                print(f"  -> {best}")
        for (bq, bk), fails in sorted(rejected.items()):
            ops = ", ".join(sorted({f["operand"] for f in fails}))
            print(f"  rejected ({bq},{bk}): {len(fails)} corpus "
                  f"divergence(s) [{ops}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
