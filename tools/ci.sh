#!/usr/bin/env bash
# CI gate (reference roles: paddle/scripts/paddle_build.sh test stages,
# tools/test_op_benchmark.sh, tools/check_api_compatible.py).
#
#   tools/ci.sh            # full gate: tests + API freeze + op-bench check
#   tools/ci.sh quick      # tests only
#
# The op-benchmark regression stage only runs when a baseline exists
# (tools/op_bench_baseline.json — record one on your hardware with
# `python tools/op_bench.py --save tools/op_bench_baseline.json`).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== pytest =="
# slow-marked tests (e.g. the SIGKILL-mid-save chaos test) run once, in
# the chaos lane below — not here
python -m pytest tests/ -q -m "not slow"

if [ "${1:-}" = "quick" ]; then exit 0; fi

echo "== chaos fault-injection lane (fixed seed, incl. slow) =="
# re-runs the fault-injection suite with the registry seeded through the
# ENV path (FLAGS_chaos_seed), proving the launcher-side arming channel
# end-to-end and pinning determinism
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    python -m pytest tests/test_chaos.py -q

echo "== elastic membership/re-form lane (fixed seed, incl. slow) =="
# the job-level recovery tier: lease-expiry shrink to loss parity,
# hang-watchdog kill+replace, SIGKILL-a-worker-mid-epoch multi-process
# re-form — deterministic (fake clock + fixed chaos seed)
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    python -m pytest tests/test_elastic.py -q

echo "== observability lane (traced mini train -> trace_merge -> schema; prometheus grammar; cluster collector) =="
# 3-step mini train with tracing armed, per-process span file merged by
# tools/trace_merge.py into a chrome trace that must pass the schema
# check; monitor.export_prometheus() must round-trip through the
# Prometheus text-format grammar (incl. cumulative-bucket invariants
# and the # HELP-per-metric scraper contract).  The collector leg then
# gates the cluster telemetry plane: with collector.rpc faults injected
# the training trajectory is bit-identical to a collector-less run
# (drops counted, nothing blocks), and in a clean mini cluster
# (2 workers + 1 PS server + collector) the rank with injected step
# latency is named in the straggler report, the cluster_top view
# (schema-validated), and the cluster-level ledger record perf_report
# compare consumes
JAX_PLATFORMS=cpu python tools/obs_check.py

echo "== ingest lane (JPEG corpus -> full pipeline; stall + cache gates) =="
# a small on-disk JPEG corpus through the streaming ingest plane
# (decode -> uint8 augment -> batch collate -> double-buffered device
# transfer): asserts op_bench-style thresholds on the cache-epoch
# speedup and the overlapped input_stall_pct, and that the gauge,
# per-stage histograms and cache counters export via
# monitor.export_prometheus(); parity/chaos/fault behavior is covered
# by tests/test_ingest_pipeline.py in the pytest lane above
JAX_PLATFORMS=cpu python tools/ingest_check.py

echo "== perf health lane (traced mini train -> health_check; zero anomalies, zero steady recompiles) =="
# the health plane's decision surface end-to-end: a fixed-seed,
# fixed-shape mini train with the default detectors armed must compile
# once per jit site and trip nothing — a steady-state recompile or a
# detector anomaly on a healthy run fails here (the same gate the
# acceptance test drives with injected ps.rpc latency, inverted)
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 30 \
    --max-anomalies 0 --max-steady-recompiles 0

echo "== model numerics lane (in-jit stats; zero grad anomalies, NaN provenance) =="
# the model-signal twin of the health lane: (1) a clean mini train with
# the numerics plane armed must trip zero grad-norm anomalies and zero
# steady recompiles (arming must not churn the jit cache); (2) a run
# with ONE layer's gradient NaN-poisoned at step 20 must skip-and-
# restore, name that leaf as first_bad_leaf in the train.nan_skip
# flight event AND fire the grad-norm detector at the poisoned step
# (both gated by the implicit --nan-step provenance verdict), with
# exactly that one anomaly per drift signal and a clean baseline after
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 30 --numerics \
    --max-anomalies 0 --max-grad-anomalies 0 --max-steady-recompiles 0
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 30 \
    --nan-step 20 --max-anomalies 3 --max-grad-anomalies 1

echo "== perf observatory lane (run ledger -> span/cost join -> cross-run regression gate) =="
# (1) span<->cost attribution: a traced 3-step mini train joined with
# the PTA106 analytic cost model must yield an op-profile where every
# top-5 op has a measured ms and a finite achieved FLOP/s (--check).
# (2) two seeded PS mini-train runs appended to a fresh ledger must
# compare clean; a third run with ps.rpc latency injected from step 0
# — a level shift the in-run detector's warmup absorbs, so that run's
# own gates stay green — MUST be flagged by the cross-run compare
# (named signal, nonzero exit).  (3) the historical BENCH_r01..r05
# trajectory must import into a ledger and compare without error.
OBSV=$(mktemp -d /tmp/pt_observatory.XXXXXX)
JAX_PLATFORMS=cpu python tools/perf_report.py attribute --mini-train 3 \
    --json "$OBSV/profile.json" --check
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 15 --ps \
    --ledger "$OBSV/ledger.jsonl" --max-anomalies 0
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 15 --ps \
    --ledger "$OBSV/ledger.jsonl" --max-anomalies 0
JAX_PLATFORMS=cpu python tools/perf_report.py compare \
    --ledger "$OBSV/ledger.jsonl"
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    FLAGS_chaos_spec='{"ps.rpc": {"mode": "latency", "latency": 0.1, "every": 1}}' \
    python tools/health_check.py --mini-train 15 --ps \
    --ledger "$OBSV/ledger.jsonl" --max-anomalies 0
# the gate demands BOTH the nonzero exit AND a named REGRESSION line in
# the verdict — a comparator that crashed (tracebacks also exit 1)
# cannot fake a flag
rc=0
JAX_PLATFORMS=cpu python tools/perf_report.py compare \
    --ledger "$OBSV/ledger.jsonl" | tee "$OBSV/verdict.txt" || rc=$?
if [ "$rc" != 1 ] || ! grep -q "^REGRESSION .*ps_rpc" "$OBSV/verdict.txt"; then
  echo "observatory lane FAILED: injected ps.rpc latency run not flagged (rc=$rc)" >&2
  exit 1
fi
JAX_PLATFORMS=cpu python tools/perf_report.py import BENCH_r0*.json \
    --ledger "$OBSV/hist.jsonl"
# the historical trajectory is informational (real regressions may
# exist in it — that is the point); the lane only demands that the
# comparator RAN to a verdict — crash or parse failure fails here
rc=0
JAX_PLATFORMS=cpu python tools/perf_report.py compare \
    --ledger "$OBSV/hist.jsonl" | tee "$OBSV/hist_verdict.txt" || rc=$?
if [ "$rc" -gt 1 ] || ! grep -q "^verdict:" "$OBSV/hist_verdict.txt"; then
  echo "observatory lane FAILED: history compare did not reach a verdict (rc=$rc)" >&2
  exit 1
fi
rm -rf "$OBSV"

echo "== causal blame lane (span links -> critical path -> bottleneck-shift gate) =="
# (1) clean traced PS mini-train: the per-step blame DAG must
# reconstruct with ZERO unresolved links and its categories must sum
# to within 5% of the measured step span (--check — the partition-
# exactness acceptance).  (2) chaos leg: ps.rpc latency injected from
# step 0 must make ps_wait the named TOP blame category (--expect-top
# — "98% input stall vs PS wait" is now a computed verdict, not a
# human reading merged traces).  (3) cross-run: two clean ledgered
# runs + the latency run — each green on its OWN gates (the level
# shift hides in warmup) — must be flagged by perf_report compare on
# the blame_ps_wait_ms series BY NAME with rc 1 (a crashed comparator
# also exits 1, hence the grep)
BLAME=$(mktemp -d /tmp/pt_blame.XXXXXX)
JAX_PLATFORMS=cpu python tools/perf_report.py blame --mini-train 12 \
    --json "$BLAME/blame.json" --check
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    FLAGS_chaos_spec='{"ps.rpc": {"mode": "latency", "latency": 0.1, "every": 1}}' \
    python tools/perf_report.py blame --mini-train 12 --check \
    --expect-top ps_wait
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 12 --ps \
    --ledger "$BLAME/ledger.jsonl" --max-anomalies 0
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 12 --ps \
    --ledger "$BLAME/ledger.jsonl" --max-anomalies 0
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    FLAGS_chaos_spec='{"ps.rpc": {"mode": "latency", "latency": 0.1, "every": 1}}' \
    python tools/health_check.py --mini-train 12 --ps \
    --ledger "$BLAME/ledger.jsonl" --max-anomalies 0
rc=0
JAX_PLATFORMS=cpu python tools/perf_report.py compare \
    --ledger "$BLAME/ledger.jsonl" | tee "$BLAME/verdict.txt" || rc=$?
if [ "$rc" != 1 ] || ! grep -q "^REGRESSION .*blame_ps_wait" "$BLAME/verdict.txt"; then
  echo "blame lane FAILED: bottleneck shift to ps_wait not named (rc=$rc)" >&2
  exit 1
fi
rm -rf "$BLAME"

echo "== concurrency lint + lock watchdog lane (PTA4xx static; runtime cycle naming) =="
# static half: the in-tree sources must be PTA4xx-clean (zero errors AND
# zero warnings — every accepted pattern carries an audited pragma), the
# rule table must match the README rows, and the committed two-lock
# inversion fixture MUST be flagged (a pass suite that can't see the
# seeded bug gates nothing)
JAX_PLATFORMS=cpu python tools/prog_lint.py --threads paddle_tpu --strict
JAX_PLATFORMS=cpu python tools/prog_lint.py --list-rules --check-docs
rc=0
JAX_PLATFORMS=cpu python tools/prog_lint.py --threads \
    tests/fixtures/lock_inversion.py --format=json \
    > /tmp/pt_threads_fixture.json || rc=$?
if [ "$rc" != 1 ] || ! grep -q '"PTA401"' /tmp/pt_threads_fixture.json; then
  echo "concurrency lane FAILED: inversion fixture not flagged (rc=$rc)" >&2
  exit 1
fi
# dynamic half: executing the SAME fixture under FLAGS_lock_watchdog
# must name the same cycle in a locks.cycle flight event while the run
# completes normally (exit 0) — the static model validated by runtime
JAX_PLATFORMS=cpu FLAGS_lock_watchdog=1 \
    python tests/fixtures/lock_inversion.py | tee /tmp/pt_watchdog.txt
if ! grep -q "LOCK_CYCLE fixture.inversion.a fixture.inversion.b" \
    /tmp/pt_watchdog.txt; then
  echo "concurrency lane FAILED: watchdog did not name the cycle" >&2
  exit 1
fi
rm -f /tmp/pt_threads_fixture.json /tmp/pt_watchdog.txt

echo "== distributed-semantics lane (PTA5xx static; runtime replica-parity probe) =="
# static half: the whole package AST-lints clean at --strict AND the
# parallel-tier zoo (zero/sharded/tp/ring traced on a virtual mesh)
# carries zero PTA5xx errors/warnings; the committed divergence fixture
# MUST be flagged PTA501 naming fixture.w2 (a pass suite that can't see
# the seeded bug gates nothing)
JAX_PLATFORMS=cpu python tools/prog_lint.py --collectives paddle_tpu \
    --zoo zero_step --zoo sharded_step --zoo tp_layers \
    --zoo ring_attention --strict --no-cost
rc=0
JAX_PLATFORMS=cpu python tools/prog_lint.py --collectives \
    tests/fixtures/replica_divergence.py --format=json \
    > /tmp/pt_collectives_fixture.json || rc=$?
if [ "$rc" != 1 ] || ! grep -q '"PTA501"' /tmp/pt_collectives_fixture.json \
    || ! grep -q 'fixture.w2' /tmp/pt_collectives_fixture.json; then
  echo "distributed lane FAILED: divergence fixture not flagged (rc=$rc)" >&2
  exit 1
fi
# dynamic half: executing the SAME fixture under FLAGS_replica_parity
# must name the IDENTICAL leaf in a parity.divergence flight event
# while the run completes normally (exit 0) — static model validated
# by runtime
JAX_PLATFORMS=cpu FLAGS_replica_parity=1 \
    python tests/fixtures/replica_divergence.py | tee /tmp/pt_parity.txt
if ! grep -q "PARITY_DIVERGENCE fixture.w2" /tmp/pt_parity.txt; then
  echo "distributed lane FAILED: probe did not name fixture.w2" >&2
  exit 1
fi
# chaos leg: an injected parity.observe error is swallowed+counted and
# the probed training trajectory stays BIT-IDENTICAL to the clean run
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    python tests/fixtures/replica_divergence.py --chaos \
    | tee /tmp/pt_parity_chaos.txt
if ! grep -q "CHAOS_PARITY_BITIDENTICAL" /tmp/pt_parity_chaos.txt; then
  echo "distributed lane FAILED: chaos leg perturbed the trajectory" >&2
  exit 1
fi
rm -f /tmp/pt_collectives_fixture.json /tmp/pt_parity.txt \
    /tmp/pt_parity_chaos.txt

echo "== pallas kernel lane (PTA6xx static; interpret-mode differential oracle) =="
# static half: every in-tree pallas_call (package sources + the kernel
# zoo traced at tail-bearing shapes) must be PTA6xx-clean at --strict —
# zero errors AND zero warnings; the committed floored-grid fixture
# MUST be flagged PTA601 + PTA603 naming fixture.out (a pass suite
# that can't see the seeded tiling bug gates nothing)
JAX_PLATFORMS=cpu python tools/prog_lint.py --pallas \
    paddle_tpu/ops/pallas paddle_tpu/parallel/ring_attention.py \
    --zoo all --strict
rc=0
JAX_PLATFORMS=cpu python tools/prog_lint.py --pallas \
    tests/fixtures/pallas_oob.py --format=json \
    > /tmp/pt_pallas_fixture.json || rc=$?
if [ "$rc" != 1 ] || ! grep -q '"PTA601"' /tmp/pt_pallas_fixture.json \
    || ! grep -q '"PTA603"' /tmp/pt_pallas_fixture.json \
    || ! grep -q 'fixture.out' /tmp/pt_pallas_fixture.json; then
  echo "pallas lane FAILED: tiling fixture not flagged (rc=$rc)" >&2
  exit 1
fi
# dynamic half: the SAME fixture under FLAGS_pallas_verify must make
# the differential oracle (interpret leg vs pure-jnp reference — the
# CPU legs) name the IDENTICAL operand in a pallas.divergence flight
# event while the run completes normally (exit 0) — the static model
# validated by runtime
JAX_PLATFORMS=cpu FLAGS_pallas_verify=1 \
    python tests/fixtures/pallas_oob.py | tee /tmp/pt_pallas.txt
if ! grep -q "PALLAS_DIVERGENCE fixture.out" /tmp/pt_pallas.txt; then
  echo "pallas lane FAILED: oracle did not name fixture.out" >&2
  exit 1
fi
# chaos leg: an injected pallas.verify error is swallowed+counted
# (pallas_verify_errors_total) and the watched computation is untouched
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    python tests/fixtures/pallas_oob.py --chaos \
    | tee /tmp/pt_pallas_chaos.txt
if ! grep -q "CHAOS_PALLAS_SWALLOWED" /tmp/pt_pallas_chaos.txt; then
  echo "pallas lane FAILED: verify fault not swallowed+counted" >&2
  exit 1
fi
rm -f /tmp/pt_pallas_fixture.json /tmp/pt_pallas.txt /tmp/pt_pallas_chaos.txt

echo "== autopilot lane (telemetry -> guarded recovery actions; offline autotune) =="
# (1) clean leg: a healthy PS mini-train under the controller must take
# ZERO actions (--max-actions 0 trips on any taken decision) — the
# hysteresis/cooldown rails hold on clean telemetry.  (2) latency leg:
# an n_times-bounded ps.rpc latency storm must drive the controller to
# prefetch.deepen (--expect-action, gated BY NAME) and the post-storm
# tail blame must come back compute-topped with ps_wait under 35% —
# detection AND recovery are both computed verdicts.  (3) seeded-NaN
# leg: a 5-step NaN storm must drive scaler.tighten + resilient.restore
# and the run must still end with the correct provenance (the restore
# actually reinstalled good weights).  (4) chaos leg: the same NaN
# recipe with autopilot.act faulted — the actuator fault is swallowed
# and counted (autopilot_act_errors_total), never raised; health_check
# gates act_errors==0 on legs 1-3, so the counter is also proven wired.
# (5) autotune smoke: measure a small knob grid into a ledger, search
# it to a tuned profile, and verify a fresh run CONSUMES the profile at
# startup (autopilot.profile_applied names the source); the same ledger
# must still compare clean (knob sweeps live in extra, not summary).
AUTO=$(mktemp -d /tmp/pt_autopilot.XXXXXX)
JAX_PLATFORMS=cpu FLAGS_autopilot_interval_steps=4 \
    python tools/health_check.py --mini-train 24 --ps --autopilot \
    --max-actions 0 --max-anomalies 0 --ledger "$AUTO/ledger.jsonl"
JAX_PLATFORMS=cpu FLAGS_autopilot_interval_steps=4 FLAGS_chaos_seed=1234 \
    FLAGS_chaos_spec='{"ps.rpc": {"mode": "latency", "latency": 0.05, "every": 1, "n_times": 40}}' \
    python tools/health_check.py --mini-train 60 --ps --autopilot \
    --expect-action prefetch.deepen --blame-tail 20 \
    --max-blame ps_wait=35 --max-anomalies 50 \
    --ledger "$AUTO/ledger.jsonl"
JAX_PLATFORMS=cpu FLAGS_autopilot_interval_steps=2 \
    python tools/health_check.py --mini-train 30 --numerics \
    --nan-step 10 --nan-storm 5 --autopilot \
    --expect-action scaler.tighten --expect-action resilient.restore \
    --max-anomalies 20 --max-grad-anomalies 20 \
    --ledger "$AUTO/ledger.jsonl"
# chaos leg: fault the actuator itself — the NaN recipe still exits 0
# (fault swallowed), and the error counter names what happened
rc=0
JAX_PLATFORMS=cpu FLAGS_autopilot_interval_steps=2 FLAGS_chaos_seed=1234 \
    FLAGS_chaos_spec='{"autopilot.act": {"mode": "error", "every": 1, "n_times": 1}}' \
    python tools/health_check.py --mini-train 30 --numerics \
    --nan-step 10 --nan-storm 5 --autopilot \
    --max-anomalies 20 --max-grad-anomalies 20 \
    | tee "$AUTO/chaos.txt" || rc=$?
if [ "$rc" != 0 ] || ! grep -q "act_errors=1" "$AUTO/chaos.txt"; then
  echo "autopilot lane FAILED: actuator fault not swallowed+counted (rc=$rc)" >&2
  exit 1
fi
JAX_PLATFORMS=cpu python tools/autotune.py --ledger "$AUTO/tune.jsonl" \
    --measure --steps 10 \
    --grid "prefetch_depth=0,1;wire_dtype=f32;batch_size=8" \
    --out "$AUTO/tuned.json"
JAX_PLATFORMS=cpu FLAGS_autotune_profile="$AUTO/tuned.json" \
    python tools/health_check.py --mini-train 8 --ps \
    --max-anomalies 0 --ledger "$AUTO/tune.jsonl" \
    | tee "$AUTO/tuned_run.txt"
if ! grep -q "tuned profile applied: source=PSTrainStep" "$AUTO/tuned_run.txt"; then
  echo "autopilot lane FAILED: tuned profile not consumed at startup" >&2
  exit 1
fi
JAX_PLATFORMS=cpu python tools/perf_report.py compare \
    --ledger "$AUTO/tune.jsonl"
rm -rf "$AUTO"

echo "== durability lane (verified generations; SIGKILL-mid-async-save; bit-flip recovery; offline fsck) =="
# the durable-state plane end-to-end: (1) clean leg — three generations
# (sync + async + async) save, commit-after-verify, and restore
# bit-exact.  (2) corruption leg — a bit-flipped shard in the newest
# committed generation makes the walk land on the older verified one BY
# NAME, firing the named ckpt.corrupt flight event, with GC keeping the
# survivor; the offline fsck must then name the corrupt file and exit 1.
# (3) SIGKILL leg — a child killed mid-ASYNC-save leaves a torn,
# uncommitted generation the walk skips; recovery lands on the newest
# verified generation by name.  (4) chaos leg — ckpt.async armed ERROR
# under the fixed seed degrades every async save to a counted sync save
# and the trajectory is bit-identical to its replay.
DURA=$(mktemp -d /tmp/pt_durable.XXXXXX)
JAX_PLATFORMS=cpu python tests/fixtures/durable_ckpt.py clean \
    "$DURA/clean" | tee "$DURA/clean.txt"
grep -q "DURABLE_CLEAN gen=3" "$DURA/clean.txt" || {
  echo "durability lane FAILED: clean leg did not restore gen 3" >&2
  exit 1; }
JAX_PLATFORMS=cpu python tests/fixtures/durable_ckpt.py corrupt \
    "$DURA/corrupt" | tee "$DURA/corrupt.txt"
if ! grep -q "DURABLE_RECOVERED gen_00000001" "$DURA/corrupt.txt" \
    || ! grep -q "FLIGHT ckpt.corrupt" "$DURA/corrupt.txt"; then
  echo "durability lane FAILED: bit-flip recovery or ckpt.corrupt event missing" >&2
  exit 1
fi
# offline fsck: must NAME the corrupt shard and exit 1
rc=0
JAX_PLATFORMS=cpu python tools/ckpt_check.py verify "$DURA/corrupt" \
    | tee "$DURA/fsck.txt" || rc=$?
if [ "$rc" != 1 ] || ! grep -q "crc_mismatch" "$DURA/fsck.txt" \
    || ! grep -q "CORRUPT  gen_00000002" "$DURA/fsck.txt"; then
  echo "durability lane FAILED: fsck did not name the corrupt file (rc=$rc)" >&2
  exit 1
fi
JAX_PLATFORMS=cpu python tests/fixtures/durable_ckpt.py sigkill-parent \
    "$DURA/sigkill" | tee "$DURA/sigkill.txt"
grep -q "DURABLE_SIGKILL_RECOVERED gen_00000001" "$DURA/sigkill.txt" || {
  echo "durability lane FAILED: SIGKILL-mid-async-save recovery" >&2
  exit 1; }
JAX_PLATFORMS=cpu FLAGS_chaos_seed=1234 \
    python tests/fixtures/durable_ckpt.py chaos "$DURA/chaos" \
    | tee "$DURA/chaos.txt"
grep -q "CKPT_CHAOS_BITIDENTICAL" "$DURA/chaos.txt" || {
  echo "durability lane FAILED: armed-chaos trajectory not bit-identical" >&2
  exit 1; }
rm -rf "$DURA"

echo "== postmortem lane (incident capture -> deterministic replay -> first-divergence bisect; torn-bundle refusal; cheap-when-off) =="
# the postmortem plane end-to-end: (1) capture leg — a seeded
# train.step_grads NaN at step 3 must AUTO-capture a committed incident
# bundle (verify_bundle-clean, flight event stamped with the id, run
# ledger indexed).  (2) replay leg — tools/replay.py must rebuild the
# step from the bundle's program descriptor, re-arm the recorded chaos
# schedule, and reproduce the recorded signal naming the SAME
# first_bad_leaf; --bisect must re-execute CLEAN and land on the
# poisoned step BY NUMBER via the recorded trajectory hashes; both
# verdicts land back in the ledger and perf_report incidents joins
# them.  (3) SIGKILL leg — a capture killed mid-write leaves a torn,
# COMMIT-less directory that verify_bundle AND replay refuse.  (4)
# clean leg — disarmed, the poisoned run captures NOTHING; armed, the
# loss trajectory is BITWISE identical to the disarmed one (the ring
# is host-only reads).
PM=$(mktemp -d /tmp/pt_postmortem.XXXXXX)
JAX_PLATFORMS=cpu python tests/fixtures/postmortem_incident.py capture \
    "$PM/cap" | tee "$PM/capture.txt"
grep -q "INCIDENT_CAPTURED" "$PM/capture.txt" || {
  echo "postmortem lane FAILED: NaN skip did not capture a bundle" >&2
  exit 1; }
BUNDLE=$(grep "^INCIDENT_CAPTURED " "$PM/capture.txt" | awk '{print $2}')
LEDGER=$(grep "^INCIDENT_LEDGER " "$PM/capture.txt" | awk '{print $2}')
JAX_PLATFORMS=cpu python tools/replay.py "$BUNDLE" --ledger "$LEDGER" \
    | tee "$PM/replay.txt"
grep -q "REPLAY_REPRODUCED kind=train.nan_skip first_bad_leaf=aux_w" \
    "$PM/replay.txt" || {
  echo "postmortem lane FAILED: replay did not reproduce the recorded leaf" >&2
  exit 1; }
JAX_PLATFORMS=cpu python tools/replay.py "$BUNDLE" --bisect \
    --ledger "$LEDGER" | tee "$PM/bisect.txt"
grep -q "BISECT_DIVERGENCE step=2 leaf=aux_w" "$PM/bisect.txt" || {
  echo "postmortem lane FAILED: bisect did not land on the poisoned step" >&2
  exit 1; }
JAX_PLATFORMS=cpu python tools/perf_report.py incidents \
    --ledger "$LEDGER" | tee "$PM/incidents.txt"
grep -q "bisect:step=2,leaf=aux_w" "$PM/incidents.txt" || {
  echo "postmortem lane FAILED: ledger join lost the replay verdict" >&2
  exit 1; }
JAX_PLATFORMS=cpu python tests/fixtures/postmortem_incident.py \
    sigkill-parent "$PM/kill" | tee "$PM/kill.txt"
grep -q "INCIDENT_SIGKILL_TORN" "$PM/kill.txt" || {
  echo "postmortem lane FAILED: torn bundle not refused" >&2
  exit 1; }
JAX_PLATFORMS=cpu python tests/fixtures/postmortem_incident.py clean \
    "$PM/clean" | tee "$PM/clean.txt"
if ! grep -q "INCIDENT_DISARMED_SILENT" "$PM/clean.txt" \
    || ! grep -q "INCIDENT_BITIDENTICAL" "$PM/clean.txt"; then
  echo "postmortem lane FAILED: cheap-when-off gate (disarmed capture or armed bitwise drift)" >&2
  exit 1
fi
rm -rf "$PM"

echo "== program lint (jaxpr IR passes + jit-safety AST lint) =="
# whole-package AST lint plus the model-zoo jaxpr passes on the cheap-
# to-trace entries — elastic_step traces the resilient train step and
# lints the chaos-threaded elastic sources, so PTA301/302 cover the
# elastic.lease / elastic.worker_hang fault points; exits nonzero on any
# error-severity finding (warnings are reported but do not gate —
# promote with --strict once the corpus has been warning-clean a while)
JAX_PLATFORMS=cpu python tools/prog_lint.py paddle_tpu \
    --zoo lenet --zoo transformer_encoder --zoo elastic_step \
    --zoo ps_transport --zoo ingest --zoo health --zoo zero_step \
    --zoo numerics_step --zoo runlog --zoo collector --zoo ckpt \
    --zoo incident --format=json --min-severity warning

echo "== API signature freeze =="
JAX_PLATFORMS=cpu python tools/print_signatures.py --check

echo "== ZeRO collective byte gate (analytic wire MB per leg/dtype, dp=2) =="
# deterministic per-replica reduce-scatter/all-gather byte counts per
# wire dtype on the sharded-update step — a change that silently
# fattens a collective (or breaks the bf16=0.5x / int8~0.25x encodings)
# fails here; the fused-step wall clock is reported but NOT gated
JAX_PLATFORMS=cpu python tools/op_bench.py --zero-collectives \
    --compare tools/op_bench_baseline.json \
    --thresholds tools/op_bench_thresholds.json

echo "== fused ring collectives lane (wire-byte gate -> ledger improvement -> collective blame) =="
# (1) analytic per-leg wire MB of the chunked ring at dp=2 per wire
# dtype, gated vs baseline AND vs the f32 leg (bf16 <= 0.51x,
# int8 <= 0.26x, int4 <= 0.14x — in-function ceiling; wall clock
# reported, not gated).  (2) two clean f32 ZeRO mini-trains plus one
# int4-ring run appended to a fresh ledger: the cross-run compare MUST
# print the zero_collective_bytes_per_step series as a named
# IMPROVEMENT (bytes fell ~8x) with zero regressions — the observatory
# seeing the ring pay off.  (3) blame --check over the ring run's
# trace: the per-step DAG reconstructs (categories sum to the step
# span) and the fused path's fenced wait lands in the `collective`
# category — the same ms that ledgers as blame_collective_ms
RING=$(mktemp -d /tmp/pt_ring.XXXXXX)
JAX_PLATFORMS=cpu python tools/op_bench.py --ring-collectives \
    --compare tools/op_bench_baseline.json \
    --thresholds tools/op_bench_thresholds.json
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 12 --zero \
    --ledger "$RING/ledger.jsonl" --max-anomalies 0
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 12 --zero \
    --ledger "$RING/ledger.jsonl" --max-anomalies 0
JAX_PLATFORMS=cpu python tools/health_check.py --mini-train 12 --zero \
    --zero-wire int4 --zero-ring --trace-dir "$RING/trace" \
    --ledger "$RING/ledger.jsonl" --max-anomalies 0
rc=0
JAX_PLATFORMS=cpu python tools/perf_report.py compare \
    --ledger "$RING/ledger.jsonl" | tee "$RING/verdict.txt" || rc=$?
if [ "$rc" != 0 ] || \
   ! grep -q "^improvement .*zero_collective_bytes_per_step" "$RING/verdict.txt"; then
  echo "ring lane FAILED: int4 ring run not flagged as a wire-byte improvement (rc=$rc)" >&2
  exit 1
fi
JAX_PLATFORMS=cpu python tools/perf_report.py blame \
    --trace-dir "$RING/trace" --step-span zero.step --check \
    | tee "$RING/blame.txt"
if ! grep -q "zero.reduce_scatter \[child -> collective\]" "$RING/blame.txt"; then
  echo "ring lane FAILED: fused reduce-scatter wait not blamed as collective" >&2
  exit 1
fi
rm -rf "$RING"

echo "== replica-parity probe overhead gate (armed <= 2% step, disarmed exactly zero) =="
# armed: the probe's amortized cost at the default cadence must stay
# under 2% of the mlp1m step (in-function gate) and its analytic hash
# wire bytes are deterministic (compare gate); disarmed: zero probe
# invocations, zero compiled probe programs, step cache untouched
# (in-function gate — "exactly zero", not "small")
JAX_PLATFORMS=cpu python tools/op_bench.py --parity-probe \
    --compare tools/op_bench_baseline.json \
    --thresholds tools/op_bench_thresholds.json

echo "== PS transport byte gate (measured wire MB per op, host-side) =="
# deterministic byte counts per wire dtype — holds the line on
# transport bytes (a change that silently fattens the wire fails here);
# localhost wall-clock is reported but NOT gated
JAX_PLATFORMS=cpu python tools/op_bench.py --ps-transport \
    --compare tools/op_bench_baseline.json \
    --thresholds tools/op_bench_thresholds.json

if [ -f tools/op_bench_baseline.json ]; then
  echo "== op benchmark regression gate =="
  if [ -f tools/op_bench_thresholds.json ]; then
    # per-op thresholds sized from the measured run-to-run distribution
    # (perf/variance_study.py, max(0.15, 6×CV)); the gate is verified to
    # catch a planted 1.3x regression (tests/test_op_bench_gate.py)
    python tools/op_bench.py --compare tools/op_bench_baseline.json \
        --thresholds tools/op_bench_thresholds.json --iters 20
  else
    # no measured distribution yet: blanket fallback wide enough for
    # tunnel jitter — run perf/variance_study.py on the chip to arm
    # the real per-op thresholds
    python tools/op_bench.py --compare tools/op_bench_baseline.json \
        --threshold 1.0 --iters 20
  fi
else
  echo "== op benchmark gate skipped (no tools/op_bench_baseline.json) =="
fi
echo "CI gate passed."
