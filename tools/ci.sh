#!/usr/bin/env bash
# CI gate (reference roles: paddle/scripts/paddle_build.sh test stages,
# tools/test_op_benchmark.sh, tools/check_api_compatible.py).
#
#   tools/ci.sh            # full gate: tests + API freeze + op-bench check
#   tools/ci.sh quick      # tests only
#
# The op-benchmark regression stage only runs when a baseline exists
# (tools/op_bench_baseline.json — record one on your hardware with
# `python tools/op_bench.py --save tools/op_bench_baseline.json`).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== pytest =="
python -m pytest tests/ -q

if [ "${1:-}" = "quick" ]; then exit 0; fi

echo "== API signature freeze =="
JAX_PLATFORMS=cpu python tools/print_signatures.py --check

if [ -f tools/op_bench_baseline.json ]; then
  echo "== op benchmark regression gate =="
  # threshold sized for remote-chip timing variance (the tunnel adds
  # up to ~2x run-to-run jitter); real regressions are larger still
  python tools/op_bench.py --compare tools/op_bench_baseline.json \
      --threshold 1.0 --iters 20
else
  echo "== op benchmark gate skipped (no tools/op_bench_baseline.json) =="
fi
echo "CI gate passed."
