#!/usr/bin/env python
"""Merge per-process tracer span files into one chrome-trace JSON.

Each process running with FLAGS_trace_dir (or an explicit
``observability.Tracer``) appends finished spans to its own
``trace_<label>.jsonl``.  This tool merges those files into a single
chrome://tracing / Perfetto-loadable JSON:

* one **pid lane per input file** (the process's label becomes the
  lane name via a ``process_name`` metadata event);
* **clock-offset correction**: each file's ``process`` meta record
  carries the offset (seconds) measured against the reference clock
  (``PsClient.sync_clock`` over the ``hello`` handshake); it is added
  to every span timestamp so all lanes share one timeline;
* span args keep the trace/span/parent ids and status, so a client
  RPC and the server-side child it caused can be matched in the UI
  (same ``trace``; child's ``parent`` == client span id).

Usage::

    python tools/trace_merge.py --out merged.json trace_a.jsonl ...
    python tools/trace_merge.py --out merged.json --dir /tmp/traces
    python tools/trace_merge.py --summary --dir /tmp/traces

``--summary`` prints a per-span-name aggregate table (count, total /
mean / p99 / max ms, error count) from the merged trace — a trace is
readable at the terminal without ever opening Chrome.  ``--out`` is
optional with ``--summary``.  ``--summary-json PATH`` writes the same
rows machine-readable (``{"schema_version", "rows"}``) so downstream
consumers — ``tools/perf_report.py attribute``, the run ledger — join
them instead of scraping the table.  ``--dir`` matching zero
``trace_*.jsonl`` files is an error (clear message, nonzero exit), not
an empty merged trace.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Tuple

__all__ = ["load_span_file", "merge", "validate_chrome_trace",
           "summarize", "format_summary", "main"]


def load_span_file(path: str) -> Tuple[dict, List[dict]]:
    """Read one tracer JSONL file → (process meta, span records).
    Later ``process`` meta lines win (sync_clock re-emits with the
    freshest offset); malformed lines are skipped, not fatal — a trace
    torn by a crash should still merge."""
    meta = {"label": os.path.basename(path), "pid": 0, "clock_offset": 0.0}
    spans: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            kind = rec.get("kind")
            if kind == "process":
                meta.update({k: rec[k] for k in
                             ("label", "pid", "clock_offset") if k in rec})
            elif kind == "span":
                spans.append(rec)
    return meta, spans


def merge(paths: List[str]) -> dict:
    """Merge span files into one chrome-trace dict.  Lane pids are the
    file index (stable and distinct even for in-process multi-role runs
    that share one OS pid); real pids land in the lane metadata."""
    events: List[dict] = []
    lanes = []
    for lane, path in enumerate(paths):
        meta, spans = load_span_file(path)
        lanes.append({"lane": lane, "file": os.path.basename(path),
                      "label": meta["label"], "os_pid": meta["pid"],
                      "clock_offset": meta["clock_offset"],
                      "spans": len(spans)})
        events.append({"name": "process_name", "ph": "M", "pid": lane,
                       "tid": 0,
                       "args": {"name": f"{meta['label']} "
                                        f"(pid {meta['pid']})"}})
        shift_us = float(meta["clock_offset"]) * 1e6
        for sp in spans:
            events.append({
                "name": sp.get("name", "?"), "ph": "X", "pid": lane,
                "tid": sp.get("tid", 0),
                "ts": float(sp.get("ts", 0.0)) + shift_us,
                "dur": float(sp.get("dur", 0.0)),
                "cat": sp.get("status", "ok"),
                "args": {"trace": sp.get("trace"), "span": sp.get("span"),
                         "parent": sp.get("parent"),
                         "status": sp.get("status"),
                         **(sp.get("attrs") or {})}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"files": lanes}}


def validate_chrome_trace(trace: dict) -> int:
    """Schema check for the merged artifact (the CI lane's gate): a
    ``traceEvents`` list of well-formed events — every event has a str
    ``name``/``ph`` and int ``pid``; complete (``X``) events carry
    numeric non-negative ``ts``/``dur``; metadata (``M``) events carry
    ``args``.  Returns the number of ``X`` span events; raises
    ``ValueError`` on the first violation."""
    if not isinstance(trace, dict):
        raise ValueError("trace must be a JSON object")
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("missing traceEvents list")
    n_spans = 0
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}]: not an object")
        if not isinstance(ev.get("name"), str) or \
                not isinstance(ev.get("ph"), str):
            raise ValueError(f"traceEvents[{i}]: name/ph missing")
        if not isinstance(ev.get("pid"), int):
            raise ValueError(f"traceEvents[{i}]: pid must be an int")
        if ev["ph"] == "X":
            for k in ("ts", "dur"):
                v = ev.get(k)
                if not isinstance(v, (int, float)) or v < 0:
                    raise ValueError(
                        f"traceEvents[{i}]: X event needs numeric "
                        f"non-negative {k}")
            n_spans += 1
        elif ev["ph"] == "M":
            if not isinstance(ev.get("args"), dict):
                raise ValueError(f"traceEvents[{i}]: M event needs args")
    return n_spans


def summarize(trace: dict) -> List[dict]:
    """Per-span-name aggregates over a merged chrome-trace dict: count,
    total/mean/p99/max ms, and how many spans closed with an error
    status.  Rows sorted by total time, heaviest first — the terminal
    answer to "where did the time go" without opening Chrome."""
    durs: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    for ev in trace.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        durs.setdefault(name, []).append(float(ev.get("dur", 0.0)) / 1e3)
        status = (ev.get("args") or {}).get("status", ev.get("cat"))
        if status == "error":
            errors[name] = errors.get(name, 0) + 1
    rows = []
    for name, ms in durs.items():
        ms.sort()
        n = len(ms)
        p99 = ms[min(n - 1, max(0, int(0.99 * n + 0.5) - 1))]
        rows.append({"name": name, "count": n,
                     "total_ms": round(sum(ms), 3),
                     "mean_ms": round(sum(ms) / n, 3),
                     "p99_ms": round(p99, 3),
                     "max_ms": round(ms[-1], 3),
                     "errors": errors.get(name, 0)})
    rows.sort(key=lambda r: r["total_ms"], reverse=True)
    return rows


def format_summary(rows: List[dict]) -> str:
    """Render :func:`summarize` rows as an aligned text table."""
    cols = ("name", "count", "total_ms", "mean_ms", "p99_ms", "max_ms",
            "errors")
    table = [cols] + [tuple(str(r[c]) for c in cols) for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = []
    for j, row in enumerate(table):
        lines.append("  ".join(
            cell.ljust(widths[i]) if i == 0 else cell.rjust(widths[i])
            for i, cell in enumerate(row)))
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("inputs", nargs="*", help="trace_*.jsonl span files")
    ap.add_argument("--dir", default=None,
                    help="merge every trace_*.jsonl under this directory")
    ap.add_argument("--out", default=None, help="merged chrome-trace path")
    ap.add_argument("--summary", action="store_true",
                    help="print a per-span-name aggregate table "
                         "(count, total/mean/p99/max ms, errors)")
    ap.add_argument("--summary-json", default=None, metavar="PATH",
                    help="write the summary rows as JSON "
                         "({schema_version, rows}) — the machine-"
                         "readable twin of --summary")
    a = ap.parse_args(argv)
    if a.out is None and not a.summary and a.summary_json is None:
        ap.error("nothing to do: pass --out, --summary and/or "
                 "--summary-json")
    paths = list(a.inputs)
    if a.dir:
        dir_paths = sorted(glob.glob(os.path.join(a.dir,
                                                  "trace_*.jsonl")))
        if not dir_paths and not paths:
            # an empty merged trace out of a typo'd/cold directory is a
            # false green (a CI lane would "pass" on nothing): refuse —
            # unless explicit input files were also given, which still
            # merge on their own
            print(f"trace_merge: --dir {a.dir}: no trace_*.jsonl span "
                  "files found (tracing off, wrong directory, or the "
                  "run wrote nothing)", file=sys.stderr)
            return 1
        paths += dir_paths
    if not paths:
        print("trace_merge: no input span files", file=sys.stderr)
        return 1
    trace = merge(paths)
    n = validate_chrome_trace(trace)
    if a.out is not None:
        with open(a.out, "w") as f:
            json.dump(trace, f)
        traces = {e["args"].get("trace") for e in trace["traceEvents"]
                  if e["ph"] == "X"}
        print(f"trace_merge: {len(paths)} file(s) -> {a.out} "
              f"({n} spans, {len(traces)} trace ids)")
    if a.summary or a.summary_json is not None:
        rows = summarize(trace)
        if a.summary:
            print(format_summary(rows))
        if a.summary_json is not None:
            with open(a.summary_json, "w") as f:
                json.dump({"schema_version": 1, "files": len(paths),
                           "rows": rows}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
