#!/usr/bin/env python
"""Merge per-process tracer span files into one chrome-trace JSON.

Each process running with FLAGS_trace_dir (or an explicit
``observability.Tracer``) appends finished spans to its own
``trace_<label>.jsonl``.  This tool merges those files into a single
chrome://tracing / Perfetto-loadable JSON:

* one **pid lane per input file** (the process's label becomes the
  lane name via a ``process_name`` metadata event);
* **clock-offset correction**: each file's ``process`` meta record
  carries the offset (seconds) measured against the reference clock
  (``PsClient.sync_clock`` over the ``hello`` handshake); it is added
  to every span timestamp so all lanes share one timeline;
* span args keep the trace/span/parent ids and status, so a client
  RPC and the server-side child it caused can be matched in the UI
  (same ``trace``; child's ``parent`` == client span id);
* **causal links** (``Span.link`` — prefetch -> consuming step, ingest
  fetch -> step, deferred push -> push_pull RPC) render as flow-event
  pairs (``ph: "s"``/``"f"``) so Perfetto draws the hand-off arrows;
  ``validate_chrome_trace`` additionally gates link integrity (every
  link resolves, the link graph is acyclic).

Usage::

    python tools/trace_merge.py --out merged.json trace_a.jsonl ...
    python tools/trace_merge.py --out merged.json --dir /tmp/traces
    python tools/trace_merge.py --summary --dir /tmp/traces

``--summary`` prints a per-span-name aggregate table (count, total /
mean / p99 / max ms, error count) from the merged trace — a trace is
readable at the terminal without ever opening Chrome.  ``--out`` is
optional with ``--summary``.  ``--summary-json PATH`` writes the same
rows machine-readable (``{"schema_version", "rows"}``) so downstream
consumers — ``tools/perf_report.py attribute``, the run ledger — join
them instead of scraping the table.  ``--dir`` matching zero
``trace_*.jsonl`` files is an error (clear message, nonzero exit), not
an empty merged trace.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Tuple

__all__ = ["load_span_file", "merge", "validate_chrome_trace",
           "summarize", "format_summary", "main"]


def load_span_file(path: str) -> Tuple[dict, List[dict]]:
    """Read one tracer JSONL file → (process meta, span records).
    Later ``process`` meta lines win (sync_clock re-emits with the
    freshest offset); malformed lines are skipped, not fatal — a trace
    torn by a crash should still merge."""
    meta = {"label": os.path.basename(path), "pid": 0, "clock_offset": 0.0}
    spans: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            kind = rec.get("kind")
            if kind == "process":
                meta.update({k: rec[k] for k in
                             ("label", "pid", "clock_offset") if k in rec})
            elif kind == "span":
                spans.append(rec)
    return meta, spans


def merge(paths: List[str]) -> dict:
    """Merge span files into one chrome-trace dict.  Lane pids are the
    file index (stable and distinct even for in-process multi-role runs
    that share one OS pid); real pids land in the lane metadata.

    Causal span links (``Span.link`` — async hand-offs: prefetch ->
    consuming step, ingest fetch -> step, deferred push -> push_pull
    RPC) are kept in the consuming event's ``args["links"]`` AND
    rendered as chrome-trace flow events (``ph: "s"`` at the producer
    span's end, ``ph: "f"``/``bp: "e"`` at the consumer's start), so
    Perfetto draws the arrow across lanes.  A link whose producer span
    is absent from the merged set stays in args (no flow pair) —
    :func:`validate_chrome_trace` flags it."""
    events: List[dict] = []
    lanes = []
    span_events: List[dict] = []
    span_index: Dict[str, dict] = {}    # span id -> its X event
    for lane, path in enumerate(paths):
        # a rotated previous segment (<path>.1, FLAGS_trace_max_mb) is
        # the same logical trace: fold it in first (older spans), with
        # the current segment's process meta winning — so links into
        # the previous segment resolve and summaries cover both
        meta, spans = load_span_file(path)
        if os.path.exists(path + ".1"):
            _, spans1 = load_span_file(path + ".1")
            spans = spans1 + spans      # current segment's meta wins
                                        # (a fresh segment re-emits it)
        lanes.append({"lane": lane, "file": os.path.basename(path),
                      "label": meta["label"], "os_pid": meta["pid"],
                      "clock_offset": meta["clock_offset"],
                      "spans": len(spans)})
        events.append({"name": "process_name", "ph": "M", "pid": lane,
                       "tid": 0,
                       "args": {"name": f"{meta['label']} "
                                        f"(pid {meta['pid']})"}})
        shift_us = float(meta["clock_offset"]) * 1e6
        for sp in spans:
            args = {"trace": sp.get("trace"), "span": sp.get("span"),
                    "parent": sp.get("parent"),
                    "status": sp.get("status"),
                    **(sp.get("attrs") or {})}
            if sp.get("links"):
                args["links"] = sp["links"]
            ev = {
                "name": sp.get("name", "?"), "ph": "X", "pid": lane,
                "tid": sp.get("tid", 0),
                "ts": float(sp.get("ts", 0.0)) + shift_us,
                "dur": float(sp.get("dur", 0.0)),
                "cat": sp.get("status", "ok"),
                "args": args}
            events.append(ev)
            span_events.append(ev)
            sid = sp.get("span")
            if sid is not None:
                span_index[str(sid)] = ev
    # second pass: one flow-event pair per RESOLVED link
    flow_id = 0
    for ev in span_events:
        for link in ev["args"].get("links") or ():
            src = span_index.get(str(link.get("span")))
            if src is None:
                continue
            flow_id += 1
            kind = str(link.get("kind", "link"))
            events.append({"name": kind, "cat": "link", "ph": "s",
                           "id": flow_id, "pid": src["pid"],
                           "tid": src["tid"],
                           "ts": src["ts"] + src["dur"]})
            events.append({"name": kind, "cat": "link", "ph": "f",
                           "bp": "e", "id": flow_id, "pid": ev["pid"],
                           "tid": ev["tid"], "ts": ev["ts"]})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"files": lanes}}


def validate_chrome_trace(trace: dict) -> int:
    """Schema check for the merged artifact (the CI lane's gate): a
    ``traceEvents`` list of well-formed events — every event has a str
    ``name``/``ph`` and int ``pid``; complete (``X``) events carry
    numeric non-negative ``ts``/``dur``; metadata (``M``) events carry
    ``args``; flow events (``s``/``f``) carry numeric ``ts`` and an
    ``id``, and every flow id forms exactly one start/finish pair.

    Link integrity (the causal layer's gate): every ``args["links"]``
    entry on an X event must RESOLVE to an X event in the merge (a
    dangling link means a producer span never closed or was lost — the
    blame DAG would silently under-attribute), and the link graph must
    be acyclic ("A waited for B waited for A" is not a causal history).

    Returns the number of ``X`` span events; raises ``ValueError`` on
    the first violation."""
    if not isinstance(trace, dict):
        raise ValueError("trace must be a JSON object")
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("missing traceEvents list")
    n_spans = 0
    span_ids = set()
    links: Dict[str, List[str]] = {}     # consumer span id -> producers
    flow_starts: Dict[object, int] = {}
    flow_ends: Dict[object, int] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}]: not an object")
        if not isinstance(ev.get("name"), str) or \
                not isinstance(ev.get("ph"), str):
            raise ValueError(f"traceEvents[{i}]: name/ph missing")
        if not isinstance(ev.get("pid"), int):
            raise ValueError(f"traceEvents[{i}]: pid must be an int")
        if ev["ph"] == "X":
            for k in ("ts", "dur"):
                v = ev.get(k)
                if not isinstance(v, (int, float)) or v < 0:
                    raise ValueError(
                        f"traceEvents[{i}]: X event needs numeric "
                        f"non-negative {k}")
            n_spans += 1
            args = ev.get("args") or {}
            sid = args.get("span")
            if sid is not None:
                span_ids.add(str(sid))
            lks = args.get("links")
            if lks is not None:
                if not isinstance(lks, list):
                    raise ValueError(
                        f"traceEvents[{i}]: links must be a list")
                for lk in lks:
                    if not isinstance(lk, dict) or "span" not in lk:
                        raise ValueError(
                            f"traceEvents[{i}]: malformed link {lk!r}")
                    if sid is not None:
                        links.setdefault(str(sid), []).append(
                            str(lk["span"]))
        elif ev["ph"] == "M":
            if not isinstance(ev.get("args"), dict):
                raise ValueError(f"traceEvents[{i}]: M event needs args")
        elif ev["ph"] in ("s", "f"):
            if not isinstance(ev.get("ts"), (int, float)):
                raise ValueError(
                    f"traceEvents[{i}]: flow event needs numeric ts")
            if "id" not in ev:
                raise ValueError(f"traceEvents[{i}]: flow event needs id")
            bucket = flow_starts if ev["ph"] == "s" else flow_ends
            bucket[ev["id"]] = bucket.get(ev["id"], 0) + 1
    # flow pairing: each id exactly one s and one f
    for fid, n in flow_starts.items():
        if n != 1 or flow_ends.get(fid, 0) != 1:
            raise ValueError(f"flow id {fid!r}: not exactly one "
                             "start/finish pair")
    for fid in flow_ends:
        if fid not in flow_starts:
            raise ValueError(f"flow id {fid!r}: finish without start")
    # every link resolves
    for consumer, producers in links.items():
        for p in producers:
            if p not in span_ids:
                raise ValueError(
                    f"span {consumer}: link to unknown span {p}")
    # no cycles in the link graph (iterative DFS, 3-color)
    color: Dict[str, int] = {}
    for root in links:
        if color.get(root):
            continue
        stack = [(root, iter(links.get(root, ())))]
        color[root] = 1
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                c = color.get(nxt, 0)
                if c == 1:
                    raise ValueError(
                        f"link cycle through span {nxt}")
                if c == 0:
                    color[nxt] = 1
                    stack.append((nxt, iter(links.get(nxt, ()))))
                    advanced = True
                    break
            if not advanced:
                color[node] = 2
                stack.pop()
    return n_spans


def summarize(trace: dict) -> List[dict]:
    """Per-span-name aggregates over a merged chrome-trace dict: count,
    total/mean/p99/max ms, and how many spans closed with an error
    status.  Rows sorted by total time, heaviest first — the terminal
    answer to "where did the time go" without opening Chrome."""
    durs: Dict[str, List[float]] = {}
    errors: Dict[str, int] = {}
    categories: Dict[str, str] = {}
    for ev in trace.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        durs.setdefault(name, []).append(float(ev.get("dur", 0.0)) / 1e3)
        args = ev.get("args") or {}
        status = args.get("status", ev.get("cat"))
        if status == "error":
            errors[name] = errors.get(name, 0) + 1
        cat = args.get("category")
        if cat is not None and name not in categories:
            categories[name] = str(cat)
    rows = []
    for name, ms in durs.items():
        ms.sort()
        n = len(ms)
        # single-sample group: the p99 IS that sample — pinned, since
        # blame tooling consumes --summary-json rows directly
        p99 = ms[0] if n == 1 else \
            ms[min(n - 1, max(0, int(0.99 * n + 0.5) - 1))]
        row = {"name": name, "count": n,
               "total_ms": round(sum(ms), 3),
               "mean_ms": round(sum(ms) / n, 3),
               "p99_ms": round(p99, 3),
               "max_ms": round(ms[-1], 3),
               "errors": errors.get(name, 0)}
        if name in categories:
            # the span's blame category attr rides along so downstream
            # consumers (framework/blame.py, perf_report) can bucket
            # summary rows without re-reading the raw trace
            row["category"] = categories[name]
        rows.append(row)
    rows.sort(key=lambda r: r["total_ms"], reverse=True)
    return rows


def format_summary(rows: List[dict]) -> str:
    """Render :func:`summarize` rows as an aligned text table."""
    cols = ("name", "count", "total_ms", "mean_ms", "p99_ms", "max_ms",
            "errors")
    table = [cols] + [tuple(str(r[c]) for c in cols) for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
    lines = []
    for j, row in enumerate(table):
        lines.append("  ".join(
            cell.ljust(widths[i]) if i == 0 else cell.rjust(widths[i])
            for i, cell in enumerate(row)))
        if j == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[1])
    ap.add_argument("inputs", nargs="*", help="trace_*.jsonl span files")
    ap.add_argument("--dir", default=None,
                    help="merge every trace_*.jsonl under this directory")
    ap.add_argument("--out", default=None, help="merged chrome-trace path")
    ap.add_argument("--summary", action="store_true",
                    help="print a per-span-name aggregate table "
                         "(count, total/mean/p99/max ms, errors)")
    ap.add_argument("--summary-json", default=None, metavar="PATH",
                    help="write the summary rows as JSON "
                         "({schema_version, rows}) — the machine-"
                         "readable twin of --summary")
    a = ap.parse_args(argv)
    if a.out is None and not a.summary and a.summary_json is None:
        ap.error("nothing to do: pass --out, --summary and/or "
                 "--summary-json")
    paths = list(a.inputs)
    if a.dir:
        dir_paths = sorted(glob.glob(os.path.join(a.dir,
                                                  "trace_*.jsonl")))
        if not dir_paths and not paths:
            # an empty merged trace out of a typo'd/cold directory is a
            # false green (a CI lane would "pass" on nothing): refuse —
            # unless explicit input files were also given, which still
            # merge on their own
            print(f"trace_merge: --dir {a.dir}: no trace_*.jsonl span "
                  "files found (tracing off, wrong directory, or the "
                  "run wrote nothing)", file=sys.stderr)
            return 1
        paths += dir_paths
    if not paths:
        print("trace_merge: no input span files", file=sys.stderr)
        return 1
    trace = merge(paths)
    n = validate_chrome_trace(trace)
    if a.out is not None:
        with open(a.out, "w") as f:
            json.dump(trace, f)
        traces = {e["args"].get("trace") for e in trace["traceEvents"]
                  if e["ph"] == "X"}
        print(f"trace_merge: {len(paths)} file(s) -> {a.out} "
              f"({n} spans, {len(traces)} trace ids)")
    if a.summary or a.summary_json is not None:
        rows = summarize(trace)
        if a.summary:
            print(format_summary(rows))
        if a.summary_json is not None:
            with open(a.summary_json, "w") as f:
                json.dump({"schema_version": 1, "files": len(paths),
                           "rows": rows}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
