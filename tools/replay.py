#!/usr/bin/env python
"""Deterministic incident replay + first-divergence bisection.

The consumer half of the postmortem plane
(``paddle_tpu/framework/incident.py``): given one incident bundle, this
tool re-executes the recorded step window standalone and proves — or
disproves — that the recorded signal reproduces.

* **replay** (default) — verify the bundle (a torn directory is
  refused, exactly like the generation walk refuses a torn
  checkpoint), rebuild the step surface from the bundle's program
  descriptor (``module:function`` builder), restore the recorded
  training state (inline bundle state, or the referenced checkpoint
  generation — a GC'd generation fails LOUDLY naming ``gen_<N>``
  rather than replaying from the wrong state), re-arm the recorded
  flags + the mid-sequence chaos schedule
  (``chaos.restore_state``), re-feed the ringed inputs with each
  entry's rng state, and gate that the recorded flight kind fires
  again with the SAME ``first_bad_leaf``.  Prints
  ``REPLAY_REPRODUCED kind=<k> first_bad_leaf=<leaf>`` (rc 0) or
  ``REPLAY_NOT_REPRODUCED ...`` (rc 1); refusals print
  ``REPLAY_REFUSED``/``REPLAY_MISSING_GENERATION`` (rc 2).

* ``--bisect`` — re-execute the ring with chaos DISARMED and walk the
  recorded per-step trajectory hashes
  (``parity.leaf_hash_host``; entry i's post-state is entry i+1's
  pre-state, the last entry's is the live state at capture): the first
  step whose clean re-execution hashes differently from the recorded
  trajectory is the poisoned step — the recorded state absorbed the
  fault there, the clean counterfactual did not.  Prints
  ``BISECT_DIVERGENCE step=<n> leaf=<name>`` (rc 0) or
  ``BISECT_CLEAN`` when the whole ring re-executes bit-identically
  (rc 1 — the incident did not come from the recorded window).

* ``--ledger PATH`` — append a ``kind=incident_replay`` record carrying
  the ``replay_verdict`` back to the run ledger, so ``perf_report
  incidents`` shows reproduced-vs-not next to each captured incident.

Usage::

    python tools/replay.py /path/incidents/incident_000001
    python tools/replay.py /path/incidents/incident_000001 --bisect
    python tools/replay.py bundle --ledger runs/ledger.jsonl
"""
from __future__ import annotations

import argparse
import importlib
import os
import sys
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

__all__ = ["load_bundle", "build_program", "restore_state",
           "apply_recorded_flags", "replay_signal", "bisect_ring", "main"]

#: flags a replay must NOT adopt from the bundle: the capture plane
#: itself (a replay must never capture its own incidents), producer
#: paths (ledger/trace/collector endpoints of the ORIGINAL run), and
#: the chaos flags (chaos.restore_state owns the schedule)
_FLAG_SKIP = {"incident", "incident_dir", "incident_kinds", "runlog_dir",
              "trace_dir", "flight_dir", "collector_endpoint",
              "chaos_spec", "chaos_seed"}


def load_bundle(path: str) -> dict:
    """Verify + read one bundle; raises SystemExit(2) with the refusal
    sentinel on a torn directory."""
    from paddle_tpu.framework import incident
    problems = incident.verify_bundle(path)
    if problems:
        reasons = "; ".join(f"{p.get('file')}: {p.get('reason')}"
                            for p in problems[:4])
        print(f"REPLAY_REFUSED bundle={path} problems={reasons}")
        raise SystemExit(2)
    return incident.read_manifest(path)


def apply_recorded_flags(manifest: dict) -> None:
    """Re-arm the recorded flag overrides (skipping the capture plane's
    own flags and unknown names — schema skew degrades, never crashes),
    then force the incident plane off for the replaying process."""
    from paddle_tpu.framework import flags
    for name, value in (manifest.get("flags_overrides") or {}).items():
        if name in _FLAG_SKIP:
            continue
        try:
            flags.set_flags({name: value})
        except ValueError:
            print(f"replay: skipping unknown recorded flag {name!r}",
                  file=sys.stderr)
    flags.set_flags({"incident": False})


def build_program(manifest: dict):
    """Rebuild the step surface from the bundle's program descriptor."""
    prog = manifest.get("program")
    if not prog or not prog.get("builder"):
        print("REPLAY_REFUSED no program descriptor in bundle (the "
              "recording process never called incident.set_program)")
        raise SystemExit(2)
    mod_name, _, fn_name = str(prog["builder"]).partition(":")
    try:
        mod = importlib.import_module(mod_name)
        builder = getattr(mod, fn_name)
    except (ImportError, AttributeError) as e:
        print(f"REPLAY_REFUSED builder {prog['builder']!r} not "
              f"importable: {e!r}")
        raise SystemExit(2)
    return builder(**(prog.get("kwargs") or {}))


def restore_state(step, manifest: dict, bundle: str) -> None:
    """Restore the recorded pre-window training state into the rebuilt
    step: the inline bundle state, or the referenced checkpoint
    generation — which must still exist, committed and verified; a GC'd
    generation fails loudly BY NAME instead of replaying from whatever
    state the fresh builder happened to initialize."""
    from paddle_tpu.distributed import checkpoint
    from paddle_tpu.framework.incident import STATE_DIRNAME, train_surface
    surface = train_surface(step)
    state = manifest.get("state") or {}
    if state.get("inline"):
        sdir = os.path.join(bundle, state.get("dir") or STATE_DIRNAME)
        checkpoint.load_train_state(surface, sdir)
        return
    ref = state.get("ref")
    if not ref or ref.get("generation") is None:
        print("REPLAY_REFUSED bundle has neither inline state nor a "
              "checkpoint generation ref (state exceeded "
              "FLAGS_incident_state_cap_mb with no durable manager "
              "attached)")
        raise SystemExit(2)
    gen = int(ref["generation"])
    gen_name = f"gen_{gen:08d}"
    gen_dir = os.path.join(str(ref.get("root") or ""), gen_name)
    if not os.path.isdir(gen_dir) or not checkpoint.is_committed(gen_dir):
        print(f"REPLAY_MISSING_GENERATION {gen_name} root={ref.get('root')}"
              " (GC'd or never committed — refusing to replay from the "
              "wrong state)")
        raise SystemExit(2)
    problems = checkpoint.verify_checkpoint(gen_dir, deep=True)
    if problems:
        print(f"REPLAY_MISSING_GENERATION {gen_name} "
              f"root={ref.get('root')} (corrupt: "
              + "; ".join(sorted({p['reason'] for p in problems})) + ")")
        raise SystemExit(2)
    checkpoint.load_train_state(surface, gen_dir)


def _materialize_inputs(bundle: str, entry: dict):
    from paddle_tpu.framework import incident
    import paddle_tpu as paddle
    loaded = incident.load_ring_entry(bundle, entry)
    args = []
    for kind, arr in loaded["inputs"]:
        args.append(paddle.to_tensor(arr) if kind == "tensor" else arr)
    return args, loaded["rng"]


def _run_ring(step, manifest: dict, bundle: str):
    """Re-execute every ringed step (entry rng re-armed per step),
    yielding (entry, loss) — shared by the replay and bisect legs."""
    from paddle_tpu.tensor.random import set_rng_state
    for entry in manifest.get("ring", []):
        args, rng = _materialize_inputs(bundle, entry)
        if rng is not None:
            set_rng_state(rng)
        yield entry, step(*args)


def replay_signal(step, manifest: dict, bundle: str) -> dict:
    """The reproduction gate: re-arm the recorded chaos schedule, re-run
    the ring, and require the recorded flight kind (same
    ``first_bad_leaf`` when one was recorded) to fire again."""
    from paddle_tpu.framework import chaos
    from paddle_tpu.framework.observability import flight
    chaos.restore_state(manifest.get("chaos") or {})
    want_kind = (manifest.get("event") or {}).get("kind")
    want_leaf = ((manifest.get("event") or {}).get("attrs") or {}) \
        .get("first_bad_leaf")
    seq0 = flight.last_seq()
    for _entry, _loss in _run_ring(step, manifest, bundle):
        pass
    got_kind, got_leaf = None, None
    for ev in flight.since(seq0, limit=1024):
        if ev.get("kind") == want_kind:
            got_kind = ev["kind"]
            got_leaf = (ev.get("attrs") or {}).get("first_bad_leaf")
            break
    reproduced = got_kind == want_kind and \
        (want_leaf is None or got_leaf == want_leaf)
    return {"reproduced": bool(reproduced), "kind": want_kind,
            "recorded_first_bad_leaf": want_leaf,
            "replayed_first_bad_leaf": got_leaf}


def bisect_ring(step, manifest: dict, bundle: str) -> dict:
    """The counterfactual walk: chaos DISARMED, re-execute the ring and
    compare each step's post-state hashes to the recorded trajectory.
    The first mismatching step is the one whose recorded execution
    absorbed the fault."""
    from paddle_tpu.framework import chaos, incident
    chaos.reset()
    trajectory = manifest.get("trajectory") or []
    post = manifest.get("post_hashes")
    ring = manifest.get("ring", [])
    i = 0
    for entry, _loss in _run_ring(step, manifest, bundle):
        expected = trajectory[i + 1].get("pre_hashes") \
            if i + 1 < len(trajectory) else post
        i += 1
        if not expected:
            continue
        live = incident.hash_step_state(step)
        for leaf in sorted(expected):
            if live.get(leaf) != int(expected[leaf]):
                return {"divergent_step": entry.get("step"),
                        "leaf": leaf, "entries_walked": i,
                        "entries_total": len(ring)}
    return {"divergent_step": None, "leaf": None,
            "entries_walked": i, "entries_total": len(ring)}


def _write_verdict(ledger_path: str, manifest: dict, verdict: dict):
    from paddle_tpu.framework import runlog
    rec = runlog.capture(
        kind="incident_replay",
        label=(manifest.get("event") or {}).get("kind"),
        include_snapshot=False,
        extra={"replay_verdict": dict(verdict,
                                      id=manifest.get("incident_id"))})
    runlog.RunLedger(ledger_path).append(rec)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="replay.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("bundle", help="incident bundle directory "
                                   "(incident_<NNNNNN>/)")
    ap.add_argument("--bisect", action="store_true",
                    help="clean-leg first-divergence walk instead of "
                         "the signal-reproduction replay")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="append the replay_verdict to this run ledger "
                         "(kind=incident_replay)")
    a = ap.parse_args(argv)

    bundle = os.path.abspath(a.bundle)
    manifest = load_bundle(bundle)
    iid = manifest.get("incident_id")
    if not manifest.get("ring"):
        print(f"REPLAY_REFUSED incident {iid}: empty input ring — "
              "nothing to re-execute")
        return 2
    apply_recorded_flags(manifest)
    step = build_program(manifest)
    restore_state(step, manifest, bundle)

    if a.bisect:
        verdict = bisect_ring(step, manifest, bundle)
        verdict["mode"] = "bisect"
        if a.ledger:
            _write_verdict(a.ledger, manifest, verdict)
        if verdict["divergent_step"] is None:
            print(f"BISECT_CLEAN incident={iid} "
                  f"entries={verdict['entries_total']}")
            return 1
        print(f"BISECT_DIVERGENCE step={verdict['divergent_step']} "
              f"leaf={verdict['leaf']} incident={iid}")
        return 0

    verdict = replay_signal(step, manifest, bundle)
    verdict["mode"] = "replay"
    if a.ledger:
        _write_verdict(a.ledger, manifest, verdict)
    if verdict["reproduced"]:
        print(f"REPLAY_REPRODUCED kind={verdict['kind']} "
              f"first_bad_leaf={verdict['recorded_first_bad_leaf']} "
              f"incident={iid}")
        return 0
    print(f"REPLAY_NOT_REPRODUCED kind={verdict['kind']} "
          f"recorded={verdict['recorded_first_bad_leaf']} "
          f"replayed={verdict['replayed_first_bad_leaf']} incident={iid}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
