#!/usr/bin/env python
"""Live cluster view — ``top`` for a paddle_tpu training fleet.

Renders one text (or JSON) snapshot of the cluster from the central
telemetry collector (``framework/collector.py`` — per-worker step
p50/p99, stall %, RPC latency, anomaly/flight counts, straggler
scores/flags, PS table request skew + hot rows), or — collector-less —
by scraping each PS server's ``stat`` op directly over the same wire
framing (the degraded view: transport/health per shard, no cross-worker
straggler scoring).

Usage::

    python tools/cluster_top.py --collector 127.0.0.1:7070
    python tools/cluster_top.py --collector 127.0.0.1:7070 --watch 2
    python tools/cluster_top.py --collector 127.0.0.1:7070 --json
    python tools/cluster_top.py --collector 127.0.0.1:7070 --capture \\
        # ask the collector to append a cluster RunRecord to its ledger
    python tools/cluster_top.py --ps 127.0.0.1:6070,127.0.0.1:6071

Exit status: 0 on a rendered view, 2 when the target is unreachable,
1 with ``--fail-on-straggler`` when the view names any straggler (the
CI gate's inverted form), 1 with ``--fail-on-incident`` when any
worker reported a captured incident bundle (the postmortem plane's
gate: a green run must not have auto-captured anything).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

__all__ = ["fetch_view", "scrape_ps", "validate_view", "render", "main"]


def fetch_view(endpoint: str, timeout: Optional[float] = None) -> dict:
    """The collector's aggregated cluster view (its ``view`` op)."""
    from paddle_tpu.framework import collector
    reply = collector.request(endpoint, {"op": "view"}, timeout=timeout)
    if not reply.get("ok"):
        raise ConnectionError(
            f"collector view failed: {reply.get('error')}")
    return reply["view"]


def scrape_ps(endpoints: List[str],
              timeout: Optional[float] = None) -> dict:
    """Collector-less fallback: scrape each PS server's ``stat`` op
    (same wire framing) into a view-shaped dict.  Per-shard transport,
    health, table skew and hot rows are real; cross-worker straggler
    scoring needs the collector and is absent."""
    from paddle_tpu.framework import collector
    workers: Dict[str, dict] = {}
    shards_by_table: Dict[str, Dict[str, dict]] = {}
    for i, ep in enumerate(endpoints):
        name = f"ps-{i}@{ep}"
        try:
            stat = collector.request(ep, {"op": "stat"}, timeout=timeout)
        except (ConnectionError, OSError) as e:
            workers[name] = {"role": "server", "error": repr(e)}
            continue
        tr = stat.get("transport") or {}
        lat = tr.get("latency_ms") or {}
        p99s = [h.get("p99") for h in lat.values() if h.get("count")]
        h_field = stat.get("health") or {}
        workers[name] = {
            "role": "server",
            "rpcs": tr.get("rpcs", 0),
            "errors": tr.get("errors", 0),
            "ps_rpc_p99_ms": max(p99s) if p99s else None,
            "anomalies_total": h_field.get("anomalies_total", 0),
            "flight_total": len(stat.get("flight") or []),
            "workers_seen": sorted(stat.get("workers") or {}),
            "dead": stat.get("dead") or [],
            "epoch": stat.get("epoch"),
        }
        for tname, t in (stat.get("table_stats") or {}).items():
            shards_by_table.setdefault(tname, {})[name] = t
    # one shared aggregation (skew formula, hot-row merge/ranking) with
    # the collector's view, so the fallback cannot silently diverge
    tables = {tname: collector.aggregate_table_shards(shards)
              for tname, shards in shards_by_table.items()}
    return {"schema_version": 1, "ts": time.time(), "source": "ps-scrape",
            "workers": workers, "tables": tables, "stragglers": [],
            "flight": [], "reports_total": 0}


def validate_view(view: dict) -> int:
    """Schema gate over a cluster view (the CI collector leg's check):
    required top-level keys, per-worker row shapes, straggler list
    consistency.  Returns the worker-row count; raises ValueError."""
    for key in ("schema_version", "ts", "workers", "tables",
                "stragglers"):
        if key not in view:
            raise ValueError(f"view missing key {key!r}")
    if not isinstance(view["workers"], dict):
        raise ValueError("view.workers is not a dict")
    for w, row in view["workers"].items():
        if not isinstance(row, dict) or "role" not in row:
            raise ValueError(f"worker row {w!r} malformed: {row!r}")
    for s in view["stragglers"]:
        if s not in view["workers"]:
            raise ValueError(f"straggler {s!r} not a known worker")
        row = view["workers"][s]
        if "straggler" in row and not row["straggler"]:
            raise ValueError(f"straggler {s!r} not flagged in its row")
    for tname, t in view["tables"].items():
        if "by_shard" not in t:
            raise ValueError(f"table {tname!r} missing by_shard")
        for rid_cnt in t.get("hot_rows") or []:
            if len(rid_cnt) != 2:
                raise ValueError(f"table {tname!r} hot_rows row "
                                 f"malformed: {rid_cnt!r}")
    return len(view["workers"])


def _fmt(v, width: int, nd: int = 1) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, bool):
        return ("YES" if v else "").rjust(width)
    if isinstance(v, float):
        return f"{v:.{nd}f}".rjust(width)
    return str(v).rjust(width)


def render(view: dict) -> str:
    """One text frame of the cluster view."""
    lines = []
    src = view.get("source", view.get("endpoint", "collector"))
    lines.append(f"== cluster_top @ {src}  "
                 f"reports={view.get('reports_total', 0)}  "
                 f"stragglers={len(view.get('stragglers') or [])} ==")
    cols = (("worker", 16), ("role", 8), ("steps", 7), ("p50ms", 8),
            ("p99ms", 8), ("stall%", 7), ("rpc_p99", 8), ("anom", 5),
            ("flight", 7), ("drops", 6), ("gaps", 5), ("inc", 4),
            ("skew", 6), ("STRAG", 6))
    lines.append("  ".join(n.rjust(w) for n, w in cols))
    for w, row in sorted((view.get("workers") or {}).items()):
        lines.append("  ".join([
            w[:16].rjust(16),
            _fmt(row.get("role"), 8),
            _fmt(row.get("steps_total", row.get("rpcs")), 7),
            _fmt(row.get("step_p50_ms"), 8, 2),
            _fmt(row.get("step_p99_ms"), 8, 2),
            _fmt(row.get("input_stall_pct"), 7),
            _fmt(row.get("ps_rpc_p99_ms"), 8, 2),
            _fmt(row.get("anomalies_total"), 5),
            _fmt(row.get("flight_total"), 7),
            _fmt(row.get("drops_reported"), 6),
            _fmt(row.get("gaps"), 5),
            _fmt(row.get("incidents_total"), 4),
            _fmt(row.get("straggler_score"), 6, 2),
            _fmt(row.get("straggler"), 6),
        ]))
    tables = view.get("tables") or {}
    if tables:
        lines.append("-- tables --")
        for tname, t in sorted(tables.items()):
            hot = "  ".join(f"{rid}:{cnt}"
                            for rid, cnt in (t.get("hot_rows") or [])[:8])
            lines.append(f"{tname}: pulls={t.get('pulls', 0)} "
                         f"pushes={t.get('pushes', 0)} "
                         f"skew={t.get('shard_skew', 1.0)}"
                         + (f"  hot: {hot}" if hot else ""))
    incidents = view.get("incidents") or []
    if incidents:
        lines.append("-- incidents --")
        for n in incidents[-8:]:
            lines.append(f"#{n.get('id', '?')} {n.get('kind', '?')} "
                         f"worker={n.get('worker', '?')} "
                         f"step={n.get('step', '?')} "
                         f"bundle={n.get('bundle', '?')}")
    flight_rows = view.get("flight") or []
    if flight_rows:
        lines.append("-- recent flight events --")
        for ev in flight_rows[-8:]:
            lines.append(f"[{ev.get('severity', '?'):5s}] "
                         f"{ev.get('worker', '?')}#{ev.get('seq', 0)} "
                         f"{ev.get('kind', '?')} {ev.get('attrs', {})}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="cluster_top.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--collector", default=None, metavar="HOST:PORT",
                    help="the central collector's endpoint "
                         "(PADDLE_COLLECTOR_ENDPOINT / launch "
                         "--collector)")
    ap.add_argument("--ps", default=None, metavar="EP[,EP...]",
                    help="collector-less fallback: scrape these PS "
                         "servers' stat ops directly")
    ap.add_argument("--watch", type=float, default=None, metavar="SEC",
                    help="re-render every SEC seconds until ^C")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw view as JSON")
    ap.add_argument("--capture", action="store_true",
                    help="ask the collector to append a cluster-level "
                         "RunRecord to its configured ledger")
    ap.add_argument("--fail-on-straggler", action="store_true",
                    help="exit 1 when the view names any straggler "
                         "(CI gate form)")
    ap.add_argument("--fail-on-incident", action="store_true",
                    help="exit 1 when any worker reported a captured "
                         "incident bundle (postmortem CI gate form)")
    ap.add_argument("--timeout", type=float, default=None)
    a = ap.parse_args(argv)
    if (a.collector is None) == (a.ps is None):
        ap.error("pass exactly one of --collector or --ps")
    if a.capture and a.collector is None:
        ap.error("--capture needs --collector")

    def one() -> int:
        try:
            if a.collector:
                view = fetch_view(a.collector, timeout=a.timeout)
            else:
                view = scrape_ps(
                    [e.strip() for e in a.ps.split(",") if e.strip()],
                    timeout=a.timeout)
        except (ConnectionError, OSError) as e:
            print(f"cluster_top: unreachable: {e}", file=sys.stderr)
            return 2
        validate_view(view)
        if a.capture:
            from paddle_tpu.framework import collector
            reply = collector.request(a.collector, {"op": "capture"},
                                      timeout=a.timeout)
            view["capture_committed"] = bool(reply.get("committed"))
        print(json.dumps(view, indent=1, default=str) if a.json
              else render(view))
        if a.fail_on_straggler and view.get("stragglers"):
            print(f"cluster_top: stragglers flagged: "
                  f"{view['stragglers']}", file=sys.stderr)
            return 1
        if a.fail_on_incident and view.get("incidents"):
            ids = sorted({f"{n.get('worker')}#{n.get('id')}"
                          for n in view["incidents"]})
            print(f"cluster_top: incidents captured: {ids}",
                  file=sys.stderr)
            return 1
        return 0

    if a.watch is None:
        return one()
    try:
        while True:
            rc = one()
            if rc != 0:
                # unreachable target (2) or a tripped
                # --fail-on-straggler gate (1): the watch form honors
                # the same exit contract as one-shot, so an alerting
                # wrapper keyed on exit status actually fires
                return rc
            time.sleep(a.watch)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
