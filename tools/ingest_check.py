#!/usr/bin/env python
"""CI gate for the streaming ingest plane (io/pipeline.py).

A small on-disk JPEG corpus through the FULL pipeline — DatasetFolder
JPEG decode -> uint8 numpy augment -> batch-granularity collate ->
IngestPipeline double-buffered device transfer — asserting op_bench-
style explicit thresholds:

1. **cache-epoch speedup**: epoch 1 records the decoded-sample cache,
   epoch 2 must drain >= ``CACHE_SPEEDUP_MIN`` x the epoch-1 rate
   (the cache's whole point: epoch >= 2 skips JPEG decode), with the
   hit/miss counters accounting for every sample;
2. **input stall**: a simulated train loop (fixed per-step compute)
   over the cached epoch must measure ``input_stall_pct`` under
   ``STALL_PCT_MAX`` — the overlap is doing its job when the consumer
   almost never waits on input;
3. the gauge and per-stage histograms must export through
   ``monitor.export_prometheus()``.

Exits non-zero on any violation.  CPU-only, deterministic corpus,
seconds.  (Exact pipelined-vs-sequential parity, chaos degradation and
worker-fault behavior are covered by tests/test_ingest_pipeline.py in
the pytest lane; this lane holds the PERFORMANCE line.)
"""
from __future__ import annotations

import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# op_bench-style thresholds: explicit, asserted, sized for a noisy
# 2-core CI host (bench.py measured 6.8x cache speedup and 0.36% stall
# on this box — these floors catch a broken cache or a serialized
# pipeline, not run-to-run jitter)
CACHE_SPEEDUP_MIN = 1.3   # epoch-2 rate / epoch-1 rate
STALL_PCT_MAX = 25.0      # consumer wait share with compute overlapped
N_IMAGES, IMG_SIZE, CROP, BATCH = 48, 96, 64, 8
STEP_MS = 10.0            # simulated per-step compute


def _gen_corpus(root):
    from PIL import Image
    rng = np.random.default_rng(7)
    for c in range(4):
        os.makedirs(os.path.join(root, f"class_{c}"), exist_ok=True)
    for i in range(N_IMAGES):
        arr = rng.integers(0, 256, size=(IMG_SIZE, IMG_SIZE, 3),
                           dtype=np.uint8)
        Image.fromarray(arr).save(
            os.path.join(root, f"class_{i % 4}", f"{i:04d}.jpg"),
            quality=85)


def _drain(pipe):
    n, t0 = 0, time.perf_counter()
    for batch in pipe:
        n += int(batch[0].shape[0])
    return n, time.perf_counter() - t0


def main() -> int:
    from paddle_tpu.framework import monitor
    from paddle_tpu.io import DataLoader, numpy_collate
    from paddle_tpu.io.pipeline import (CachedDataset, IngestPipeline,
                                        SampleCache)
    from paddle_tpu.vision import transforms as T
    from paddle_tpu.vision.datasets import DatasetFolder

    def pil_loader(path):
        from PIL import Image
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))

    with tempfile.TemporaryDirectory() as root:
        _gen_corpus(root)
        aug = T.Compose([T.RandomResizedCrop(CROP),
                         T.RandomHorizontalFlip()])
        ds = DatasetFolder(root, loader=pil_loader, extensions=(".jpg",),
                           transform=aug)
        cache = SampleCache(mode="memory", max_bytes=1 << 28)
        cds = CachedDataset(ds, cache)

        def pipeline():
            return IngestPipeline(DataLoader(
                cds, batch_size=BATCH, shuffle=False, drop_last=True,
                collate_fn=numpy_collate))

        # -- 1. cache-epoch speedup ----------------------------------------
        n1, dt1 = _drain(pipeline())        # epoch 1: decode + record
        assert cache.misses >= n1, \
            f"epoch 1 should miss every sample: {cache.misses} < {n1}"
        n2, dt2 = _drain(pipeline())        # epoch 2: cache hits
        assert cache.hits >= n2, \
            f"epoch 2 should hit every sample: {cache.hits} < {n2}"
        rate1, rate2 = n1 / dt1, n2 / dt2
        speedup = rate2 / rate1
        print(f"ingest_check: epoch1 {rate1:.0f} ex/s, epoch2 "
              f"{rate2:.0f} ex/s, cache speedup {speedup:.2f}x "
              f"(floor {CACHE_SPEEDUP_MIN}x)")
        assert speedup >= CACHE_SPEEDUP_MIN, \
            f"cache-epoch speedup {speedup:.2f}x < {CACHE_SPEEDUP_MIN}x"

        # -- 2. input stall with compute overlapped ------------------------
        pipe = pipeline()
        for batch in pipe:
            time.sleep(STEP_MS / 1e3)       # simulated train step
        stall = pipe.input_stall_pct
        print(f"ingest_check: cached-epoch input_stall_pct "
              f"{stall:.2f}% (ceiling {STALL_PCT_MAX}%)")
        assert stall < STALL_PCT_MAX, \
            f"input_stall_pct {stall:.2f} >= {STALL_PCT_MAX}"

        # -- 3. first-class export -----------------------------------------
        text = monitor.export_prometheus()
        for needle in ("input_stall_pct", "ingest_decode_ms_bucket",
                       "ingest_wait_ms_bucket",
                       "ingest_cache_hits_total",
                       "ingest_cache_misses_total"):
            assert needle in text, \
                f"{needle} missing from export_prometheus()"
        print("ingest_check: prometheus export OK")
    print("ingest_check: all gates passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
