#!/usr/bin/env python
"""Config-driven per-op benchmark harness + regression gate.

Reference roles:
  * paddle/fluid/operators/benchmark/op_tester.cc:67 — replay one op from
    an OpTesterConfig (shapes/dtypes/attrs), time repeated runs;
  * tools/test_op_benchmark.sh + tools/check_op_benchmark_result.py — the
    CI gate comparing op timings against a stored baseline.

Usage:
    python tools/op_bench.py                         # built-in suite
    python tools/op_bench.py --config cfg.json       # custom ops
    python tools/op_bench.py --save base.json        # record baseline
    python tools/op_bench.py --compare base.json --threshold 0.15
        # exit 1 if any op is >15% slower than the baseline

Config entries: {"name", "op" (dotted path under paddle_tpu),
"args" ([{shape, dtype, low?, high?} or scalar]), "kwargs"?, "grad"?}.
Timings use a device->host fetch as the execution fence (the tunnel's
block_until_ready can return early; see bench.py _sync).
"""
from __future__ import annotations

import argparse
import importlib
import json
import sys
import time

import numpy as np

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUILTIN_SUITE = [
    {"name": "matmul_1k", "op": "paddle_tpu.matmul",
     "args": [{"shape": [1024, 1024], "dtype": "float32"},
              {"shape": [1024, 1024], "dtype": "float32"}]},
    {"name": "softmax_8kx1k", "op": "paddle_tpu.nn.functional.softmax",
     "args": [{"shape": [8192, 1024], "dtype": "float32"}]},
    {"name": "layer_norm", "op": "paddle_tpu.nn.functional.layer_norm",
     "args": [{"shape": [4096, 1024], "dtype": "float32"}],
     "kwargs": {"normalized_shape": [1024]}},
    {"name": "conv2d_64", "op": "paddle_tpu.nn.functional.conv2d",
     "args": [{"shape": [8, 64, 56, 56], "dtype": "float32"},
              {"shape": [64, 64, 3, 3], "dtype": "float32"}],
     "kwargs": {"padding": 1}},
    {"name": "embedding_bag", "op": "paddle_tpu.nn.functional.embedding_bag",
     "args": [{"shape": [512, 64], "dtype": "int64", "low": 0,
               "high": 30000},
              {"shape": [30000, 128], "dtype": "float32"}],
     "kwargs": {"mode": "mean"}},
    {"name": "reduce_sum_16m", "op": "paddle_tpu.sum",
     "args": [{"shape": [4096, 4096], "dtype": "float32"}]},
]


# PS transport microbench suite (--ps-transport): an in-process PsServer
# + PsClient over localhost TCP, per wire dtype.  ``wire_mb`` (measured
# bytes on the wire per op, from the client's TransportStats) is the
# gated metric — byte counts are deterministic, so the compare gate can
# hold the line on transport bytes with a tight threshold while the
# wall-clock ms stays informational (localhost TCP timing is too noisy
# to gate).  Names here are registered with the compare gate's key
# validation like the builtin ops.
PS_TRANSPORT_SUITE = [
    {"name": "ps_pull_8kx64_f32", "kind": "pull", "wire": "f32"},
    {"name": "ps_pull_8kx64_bf16", "kind": "pull", "wire": "bf16"},
    {"name": "ps_pull_8kx64_int8", "kind": "pull", "wire": "int8"},
    {"name": "ps_push_8kx64_f32", "kind": "push", "wire": "f32"},
    {"name": "ps_push_8kx64_bf16", "kind": "push", "wire": "bf16"},
    {"name": "ps_push_pull_8kx64_bf16", "kind": "push_pull",
     "wire": "bf16"},
]


def ps_transport_bench(repeats=3):
    """Measure wire bytes + round-trip time for each PS_TRANSPORT_SUITE
    entry against an in-process server.  Device-independent (host numpy
    + TCP), so records carry device 'host' and gate everywhere."""
    from paddle_tpu.distributed.ps import HostEmbeddingTable
    from paddle_tpu.distributed.ps.service import PsClient, PsServer

    n_ids, dim, rows = 8192, 64, 65536
    srv = PsServer({"emb": HostEmbeddingTable(
        rows, dim, optimizer="sgd", learning_rate=0.0)}, port=0)
    srv.start()
    results = []
    try:
        rng = np.random.default_rng(0)
        ids = rng.integers(0, rows, size=(n_ids,)).astype(np.int64)
        grads = rng.standard_normal((n_ids, dim)).astype(np.float32)
        for cfg in PS_TRANSPORT_SUITE:
            c = PsClient([f"127.0.0.1:{srv.port}"], wire_dtype=cfg["wire"])
            ops = {
                "pull": lambda: c.pull("emb", ids),
                "push": lambda: c.push("emb", ids, grads),
                "push_pull": lambda: c.push_pull("emb", ids, grads, ids),
            }
            run = ops[cfg["kind"]]
            run()                            # warm (incl. hello handshake)
            best = None
            s0 = c.transport_stats()
            for _ in range(repeats):
                t0 = time.perf_counter()
                run()
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            s1 = c.transport_stats()
            wire_mb = ((s1["bytes_sent"] - s0["bytes_sent"]) +
                       (s1["bytes_recv"] - s0["bytes_recv"])) \
                / repeats / 1e6
            c.bye()
            r = {"name": cfg["name"], "op": f"ps.{cfg['kind']}",
                 "ms": round(best * 1e3, 3), "wire_mb": round(wire_mb, 5),
                 "device": "host"}
            results.append(r)
            print(json.dumps(r), flush=True)
    finally:
        srv.shutdown()
    return results


# ZeRO collective byte suite (--zero-collectives): the sharded-update
# train step's reduce-scatter / all-gather legs per wire dtype, on a
# fixed ~1M-param MLP at dp=2.  ``wire_mb`` is ANALYTIC (ShardedUpdate-
# TrainStep.collective_wire_bytes — exact payload accounting per leg,
# deterministic across hosts), so the compare gate holds the line on
# collective bytes with a tight threshold; ``ms`` is the measured full
# fused-step wall clock (identical for the rs/ag records of one wire —
# the legs are not separable on the host) and stays informational.
ZERO_COLLECTIVES_SUITE = [
    {"name": "zero_rs_mlp1m_f32", "leg": "reduce_scatter", "wire": "f32"},
    {"name": "zero_rs_mlp1m_bf16", "leg": "reduce_scatter",
     "wire": "bf16"},
    {"name": "zero_rs_mlp1m_int8", "leg": "reduce_scatter",
     "wire": "int8"},
    {"name": "zero_ag_mlp1m_f32", "leg": "all_gather", "wire": "f32"},
    {"name": "zero_ag_mlp1m_bf16", "leg": "all_gather", "wire": "bf16"},
    {"name": "zero_ag_mlp1m_int8", "leg": "all_gather", "wire": "int8"},
]


def zero_collectives_bench(repeats=3):
    """One sharded-update step per wire dtype on a dp=2 CPU/accelerator
    mesh; emits a record per (leg, wire) with the analytic per-replica
    wire MB (gated) and the measured step ms (informational)."""
    # dp=2 needs >= 2 devices; on a CPU host force a virtual mesh
    # BEFORE jax initializes (a no-op for non-CPU backends)
    if "jax" not in sys.modules:
        xf = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xf:
            os.environ["XLA_FLAGS"] = (
                xf + " --xla_force_host_platform_device_count=8").strip()
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.zero import ShardedUpdateTrainStep
    if len(jax.devices()) < 2:
        raise RuntimeError(
            "--zero-collectives needs >= 2 devices for a dp=2 mesh "
            "(CPU hosts get a virtual mesh automatically unless jax "
            "was already initialized single-device)")
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])

    def loss_fn(m, x, y):
        return ((m(x) - y) ** 2).mean()

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 512)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 512)).astype(np.float32))
    results = []
    by_wire = {}
    for wire in ("f32", "bf16", "int8"):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(512, 1024), nn.ReLU(),
                              nn.Linear(1024, 512))
        opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                 parameters=model.parameters())
        step = ShardedUpdateTrainStep(model, loss_fn, opt, mesh=mesh,
                                      wire_dtype=wire)
        step(x, y)                       # warm (compile)
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            loss = step(x, y)
            np.asarray(loss._data)       # execution fence
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        by_wire[wire] = (best, step.collective_wire_bytes())
    for cfg in ZERO_COLLECTIVES_SUITE:
        best, bytes_ = by_wire[cfg["wire"]]
        r = {"name": cfg["name"], "op": f"zero.{cfg['leg']}",
             "ms": round(best * 1e3, 3),
             "wire_mb": round(bytes_[cfg["leg"]] / 1e6, 5),
             "device": "host"}
        results.append(r)
        print(json.dumps(r), flush=True)
    return results


# Fused ring collective suite (--ring-collectives): the same ~1M-param
# MLP step at dp=2 with the quantized ring engaged (parallel/ring.py),
# one record per (leg, wire) across every collective wire including the
# packed int4 codec.  ``wire_mb`` is ANALYTIC and DETERMINISTIC (the
# ring moves the same (dp-1) encoded chunks per leg per replica as the
# unfused exchange — ShardedUpdateTrainStep.collective_wire_bytes), so
# the compare gate holds the line on encoded bytes; ``ms`` is the
# measured fused-step wall clock and stays informational.  The bench
# additionally gates the CODEC RATIOS in-function: each quantized
# wire's per-leg bytes must stay under its analytic ceiling relative to
# f32 (bf16 0.51x, int8 0.26x, int4 0.14x — the acceptance bars; the
# real ratios at chunk=256 are 0.500x / 0.2539x / 0.1289x).
RING_COLLECTIVES_SUITE = [
    {"name": "ring_rs_mlp1m_f32", "leg": "reduce_scatter", "wire": "f32"},
    {"name": "ring_rs_mlp1m_bf16", "leg": "reduce_scatter",
     "wire": "bf16"},
    {"name": "ring_rs_mlp1m_int8", "leg": "reduce_scatter",
     "wire": "int8"},
    {"name": "ring_rs_mlp1m_int4", "leg": "reduce_scatter",
     "wire": "int4"},
    {"name": "ring_ag_mlp1m_f32", "leg": "all_gather", "wire": "f32"},
    {"name": "ring_ag_mlp1m_bf16", "leg": "all_gather", "wire": "bf16"},
    {"name": "ring_ag_mlp1m_int8", "leg": "all_gather", "wire": "int8"},
    {"name": "ring_ag_mlp1m_int4", "leg": "all_gather", "wire": "int4"},
]

# per-leg wire-byte ceiling vs the f32 leg (analytic, chunk=256)
RING_WIRE_RATIO_MAX = {"bf16": 0.51, "int8": 0.26, "int4": 0.14}


def ring_collectives_bench(repeats=3):
    """One ring-enabled sharded-update step per wire dtype on a dp=2
    mesh; emits a record per (leg, wire) with the analytic per-replica
    wire MB (gated vs baseline AND vs the f32 leg's ratio ceiling) and
    the measured step ms (informational)."""
    if "jax" not in sys.modules:
        xf = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xf:
            os.environ["XLA_FLAGS"] = (
                xf + " --xla_force_host_platform_device_count=8").strip()
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.zero import ShardedUpdateTrainStep
    if len(jax.devices()) < 2:
        raise RuntimeError(
            "--ring-collectives needs >= 2 devices for a dp=2 mesh "
            "(CPU hosts get a virtual mesh automatically unless jax "
            "was already initialized single-device)")
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])

    def loss_fn(m, x, y):
        return ((m(x) - y) ** 2).mean()

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 512)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 512)).astype(np.float32))
    results = []
    by_wire = {}
    for wire in ("f32", "bf16", "int8", "int4"):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(512, 1024), nn.ReLU(),
                              nn.Linear(1024, 512))
        opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                 parameters=model.parameters())
        step = ShardedUpdateTrainStep(model, loss_fn, opt, mesh=mesh,
                                      wire_dtype=wire, ring=True)
        step(x, y)                       # warm (compile)
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            loss = step(x, y)
            np.asarray(loss._data)       # execution fence
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        by_wire[wire] = (best, step.collective_wire_bytes())
    # in-function ratio gate: the codec must actually shrink the wire
    for wire, cap in RING_WIRE_RATIO_MAX.items():
        for leg in ("reduce_scatter", "all_gather"):
            ratio = by_wire[wire][1][leg] / by_wire["f32"][1][leg]
            if ratio > cap:
                raise RuntimeError(
                    f"ring {wire} {leg} wire bytes are {ratio:.4f}x of "
                    f"the f32 leg (ceiling {cap}x) — the codec stopped "
                    "compressing; check wire.py wire_nbytes")
    for cfg in RING_COLLECTIVES_SUITE:
        best, bytes_ = by_wire[cfg["wire"]]
        r = {"name": cfg["name"], "op": f"ring.{cfg['leg']}",
             "ms": round(best * 1e3, 3),
             "wire_mb": round(bytes_[cfg["leg"]] / 1e6, 5),
             "device": "host"}
        results.append(r)
        print(json.dumps(r), flush=True)
    return results


# Replica-parity probe suite (--parity-probe): the runtime half of the
# distributed-semantics plane on the same ~1M-param MLP at dp=2.  The
# contract gated here: ARMED, the probe's amortized cost at the default
# cadence stays under 2% of a step (in-function gate; the probe's own
# per-invocation ms is recorded, and its ANALYTIC wire bytes — one
# uint32 hash per leaf through a psum ring — gate deterministically
# against the baseline); DISARMED, the probe adds exactly zero — zero
# probe invocations, zero compiled probe programs, zero step-cache
# churn.  "Exactly zero" is structural, so the disarmed leg is an
# in-function gate (a record still prints and reaches the ledger for
# cross-run step-time series); only the armed record enters the
# baseline compare — its wall clock may not carry a <30% threshold on
# a noisy CPU host, and the thresholds file is held to <30% by
# tests/test_op_bench_gate.py.
PARITY_PROBE_SUITE = [
    {"name": "parity_probe_mlp1m_armed"},
]


def parity_probe_bench(repeats=3, steps=10):
    if "jax" not in sys.modules:
        xf = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in xf:
            os.environ["XLA_FLAGS"] = (
                xf + " --xla_force_host_platform_device_count=8").strip()
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.framework import monitor
    from paddle_tpu.framework.flags import get_flags, set_flags
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.parity import ParityProbe, _state_tree
    from paddle_tpu.parallel.zero import ShardedUpdateTrainStep
    if len(jax.devices()) < 2:
        raise RuntimeError(
            "--parity-probe needs >= 2 devices for a dp=2 mesh")
    mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])

    def loss_fn(m, x, y):
        return ((m(x) - y) ** 2).mean()

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 512)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 512)).astype(np.float32))
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(512, 1024), nn.ReLU(),
                          nn.Linear(1024, 512))
    opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                             parameters=model.parameters())
    step = ShardedUpdateTrainStep(model, loss_fn, opt, mesh=mesh,
                                  wire_dtype="f32")
    saved = get_flags(["replica_parity", "replica_parity_every"])
    results = []
    try:
        # -- disarmed: the step must be byte-identical to the seed ----
        set_flags({"replica_parity": False})
        monitor.reset_all_stats()
        step(x, y)                              # warm (compile)
        fns_before = set(step._fns)
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(x, y)
            np.asarray(loss._data)
            dt = (time.perf_counter() - t0) / steps
            best = dt if best is None else min(best, dt)
        if monitor.get_stat("parity_checks_total"):
            raise RuntimeError("disarmed probe ran a check")
        if getattr(step, "_parity_probe", None) is not None:
            raise RuntimeError("disarmed probe attached state")
        if set(step._fns) != fns_before:
            raise RuntimeError("disarmed probe changed the step cache")
        step_ms = best * 1e3
        r = {"name": "parity_probe_mlp1m_disarmed",
             "ms": round(step_ms, 3), "probe_calls": 0,
             "device": "host"}
        results.append(r)
        print(json.dumps(r), flush=True)

        # -- armed: per-invocation probe cost + analytic wire ---------
        set_flags({"replica_parity": True})
        every = int(get_flags("replica_parity_every")
                    ["replica_parity_every"])
        probe = ParityProbe(mesh=mesh, every=1)
        tree = _state_tree(step)
        rec = probe.observe(tree)               # warm (compile)
        if rec is None or not rec.ok():
            raise RuntimeError("armed probe found divergence on a "
                               "healthy step (or probed nothing)")
        n_leaves = len(rec.names)
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(steps):
                out = probe.observe(tree)
            _ = out.divergent_leaves()          # host fetch fence
            dt = (time.perf_counter() - t0) / steps
            best = dt if best is None else min(best, dt)
        probe_ms = best * 1e3
        overhead_pct = (probe_ms / every) / step_ms * 100.0
        if overhead_pct > 2.0:
            raise RuntimeError(
                f"armed parity probe costs {overhead_pct:.2f}% of a "
                f"step at the default cadence (every={every}) — the "
                "2% budget is the flag's promise")
        # analytic wire: one uint32 hash per leaf through a psum ring
        dp = 2
        wire_mb = 2.0 * (dp - 1) / dp * 4 * n_leaves / 1e6
        r = {"name": "parity_probe_mlp1m_armed",
             "ms": round(probe_ms, 3),
             "wire_mb": round(wire_mb, 6),
             "overhead_pct": round(overhead_pct, 3),
             "leaves": n_leaves, "device": "host"}
        results.append(r)
        print(json.dumps(r), flush=True)
    finally:
        set_flags(saved)
    return results


def _resolve(path: str):
    mod, _, attr = path.rpartition(".")
    obj = importlib.import_module(mod)
    return getattr(obj, attr)


def _make_arg(spec, rng):
    import paddle_tpu as paddle
    if not isinstance(spec, dict):
        return spec
    dtype = spec.get("dtype", "float32")
    shape = spec["shape"]
    if np.issubdtype(np.dtype(dtype), np.integer):
        arr = rng.integers(spec.get("low", 0), spec.get("high", 100),
                           size=shape).astype(dtype)
    else:
        arr = rng.standard_normal(shape).astype(dtype)
    return paddle.to_tensor(arr)


def _sync(out):
    from paddle_tpu.core import Tensor
    if isinstance(out, (list, tuple)):
        out = out[0]
    arr = out._data if isinstance(out, Tensor) else out
    np.asarray(arr)


_MANY_CACHE: dict = {}
_SCAN_LEN_CACHE: dict = {}


def run_one(cfg, iters=10, repeats=3):
    """Tunnel-immune op timing via a two-length scan difference.

    The op is chained ``L`` times through one jitted lax.scan (a real
    data dependency links iterations), dispatched once.  A single
    amortized timing still carries the dispatch+fetch RTT (~90 ms here,
    swinging 1.5-2x between passes — it dominated every per-call
    estimate this replaced); timing a short scan and a long scan and
    dividing the delta by the iteration difference cancels the RTT
    exactly.  The long length is calibrated per op to ~1 s of device
    time and cached, as are the compiled scans; min-of-``repeats``
    strips residual jitter.  Baseline and CI gate share this estimator.
    Warmup needs no knob: each compiled scan gets one untimed call.

    ``iters`` sets the short length (and the calibration probe);
    regression detection quality depends on the long leg, so the
    default is fine almost always."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import Tensor

    fn = _resolve(cfg["op"])
    name = cfg.get("name", cfg["op"])
    # cache on the full config, not the name: a custom --config suite may
    # repeat an op with different args/kwargs under the same default name
    ckey = json.dumps(cfg, sort_keys=True, default=str)
    rng = np.random.default_rng(0)
    args = [_make_arg(a, rng) for a in cfg.get("args", [])]
    kwargs = cfg.get("kwargs", {})
    arrs = [a._data if isinstance(a, Tensor) else a for a in args]
    was_t = [isinstance(a, Tensor) for a in args]
    # chain the carry through the first float operand: a `* 0` dependency
    # is constant-folded and the op hoisted out of the scan (measured:
    # embedding_bag "ran" in 8.8 us); a sub-ulp runtime value is not.
    # The carry itself stays float32 UNCONDITIONALLY: an int or fp16
    # carry would turn the 1e-30 scale into a foldable constant zero
    # (int truncation / fp16 underflow at trace time) and resurrect the
    # hoisting for int-only/fp16 --config suites — the cast to the
    # operand dtype happens only at the `xs[ci] + c` use site, where the
    # carry is a runtime value XLA cannot fold
    ci = next((i for i, a in enumerate(arrs)
               if jnp.issubdtype(a.dtype, jnp.floating)), 0)

    def core(*xs):
        targs = [Tensor(x) if t else x for x, t in zip(xs, was_t)]
        out = fn(*targs, **kwargs)
        if isinstance(out, (list, tuple)):
            out = out[0]
        return out._data if isinstance(out, Tensor) else out

    def many_of(length):
        key = (ckey, length)
        got = _MANY_CACHE.get(key)
        if got is not None:
            return got

        @jax.jit
        def many(*xs):
            def body(c, _):
                mod = list(xs)
                mod[ci] = xs[ci] + c.astype(xs[ci].dtype)
                out = core(*mod)
                dep = out.mean().astype(jnp.float32) * \
                    jnp.asarray(1e-30, jnp.float32)
                return c + dep, None
            c, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), None,
                                length=length)
            return c

        _MANY_CACHE[key] = many
        return many

    def timed(many, reps):
        out = many(*arrs)                    # compile + device warm
        np.asarray(jax.device_get(out))
        best = None
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            out = many(*arrs)
            np.asarray(jax.device_get(out))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    l_small = max(4, iters)
    t_small = timed(many_of(l_small), repeats)
    l_big = _SCAN_LEN_CACHE.get(ckey)
    if l_big is None:
        l_probe = l_small + 512
        t_probe = timed(many_of(l_probe), 2)
        per_iter = max((t_probe - t_small) / (l_probe - l_small), 1e-8)
        # ~1 s of device time on the long leg: the tunnel's ±20 ms
        # dispatch jitter then contributes <3% to the difference
        l_big = l_small + int(min(max(1.0 / per_iter, 64), 400_000))
        _SCAN_LEN_CACHE[ckey] = l_big
    # a later call with a larger l_small than the cached calibration must
    # not collapse the difference leg
    l_big = max(l_big, l_small + 64)
    t_big = timed(many_of(l_big), repeats)
    dt = (t_big - t_small) / (l_big - l_small)
    if dt <= 0.0:
        # jitter swamped the difference leg (possible for very cheap ops
        # whose calibrated long leg hit the scan cap): recalibrate once
        # with a doubled difference before giving up
        l_big = l_small + 2 * (l_big - l_small)
        _SCAN_LEN_CACHE[ckey] = l_big
        t_big = timed(many_of(l_big), repeats)
        dt = (t_big - t_small) / (l_big - l_small)
    if dt <= 0.0:
        # a recorded 0.0 ms would poison any baseline it lands in (the
        # compare gate divides by it) — refuse to report a measurement
        return {"name": name, "op": cfg["op"],
                "error": "non-positive scan-difference timing after "
                         f"recalibration (t_small={t_small:.6f}s, "
                         f"t_big={t_big:.6f}s, scan_len={l_big}); "
                         "refusing to record 0.0 ms",
                "device": jax.default_backend()}
    return {"name": name, "op": cfg["op"], "ms": round(dt * 1e3, 5),
            "scan_len": l_big, "device": jax.default_backend()}


def eager_vs_jit_bench(iters=30, batch=64):
    """Quantify eager dispatch overhead: a LeNet fwd+bwd+SGD step timed
    (a) eager with the compiled (fwd,vjp) dispatch cache off,
    (b) eager with it on (the core.ops fast-path role,
        reference pybind/op_function_generator.cc), and
    (c) fully captured as one XLA computation (jit.TrainStep).
    """
    import paddle_tpu as paddle
    from paddle_tpu import jit
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.vision.models import LeNet

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((batch, 1, 28, 28))
                         .astype(np.float32))
    y = paddle.to_tensor(rng.integers(0, 10, size=(batch,)).astype(np.int64))

    def loss_fn(model, xb, yb):
        import paddle_tpu.nn.functional as F
        return F.cross_entropy(model(xb), yb)

    def eager_step(model, opt):
        loss = loss_fn(model, x, y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    results = {}
    for mode in ("eager_nocache", "eager_cached", "trainstep_jit"):
        model = LeNet()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters())
        set_flags({"eager_op_jit_cache": mode != "eager_nocache"})
        if mode == "trainstep_jit":
            step = jit.TrainStep(model, loss_fn, opt)
            run = lambda: step(x, y)                       # noqa: E731
        else:
            run = lambda: eager_step(model, opt)           # noqa: E731
        for _ in range(5):
            loss = run()
        _sync(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = run()
        _sync(loss)
        results[mode] = (time.perf_counter() - t0) / iters * 1e3
    set_flags({"eager_op_jit_cache": True})
    out = {"name": "lenet_step_dispatch", "batch": batch,
           "eager_nocache_ms": round(results["eager_nocache"], 3),
           "eager_cached_ms": round(results["eager_cached"], 3),
           "trainstep_jit_ms": round(results["trainstep_jit"], 3),
           "cache_speedup": round(
               results["eager_nocache"] / results["eager_cached"], 2),
           "jit_speedup_vs_eager": round(
               results["eager_nocache"] / results["trainstep_jit"], 2)}
    print(json.dumps(out), flush=True)
    return out


def eager_transformer_bench(iters=20, batch=8, seq=128, d_model=256):
    """Eager dispatch-cache effectiveness on a transformer block (round-4
    verdict item 9: LeNet alone doesn't show whether the ~9x transfers
    to attention-heavy eager code).  Times a TransformerEncoderLayer
    fwd+bwd+SGD eager step with the (fwd,vjp) cache off vs on, and
    reports the monitor hit/miss/uncacheable counters."""
    import paddle_tpu as paddle
    from paddle_tpu.framework import monitor
    from paddle_tpu.framework.flags import flag, set_flags

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((batch, seq, d_model))
                         .astype(np.float32))

    def eager_step(model, opt):
        out = model(x)
        loss = (out * out).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    results = {}
    stats = {}
    prior = flag("eager_op_jit_cache")
    try:
        for mode in ("nocache", "cached"):
            paddle.seed(0)
            model = paddle.nn.TransformerEncoderLayer(
                d_model=d_model, nhead=4, dim_feedforward=4 * d_model)
            opt = paddle.optimizer.SGD(learning_rate=0.01,
                                       parameters=model.parameters())
            set_flags({"eager_op_jit_cache": mode == "cached"})
            monitor.reset_all_stats()
            for _ in range(3):
                loss = eager_step(model, opt)
            _sync(loss)
            t0 = time.perf_counter()
            for _ in range(iters):
                loss = eager_step(model, opt)
            _sync(loss)
            results[mode] = (time.perf_counter() - t0) / iters * 1e3
            stats[mode] = {k: v for k, v in monitor.all_stats().items()
                           if k.startswith("eager_cache")}
    finally:
        set_flags({"eager_op_jit_cache": prior})
    s = stats["cached"]
    total = sum(s.values()) or 1
    out = {"name": "eager_transformer_block",
           "nocache_ms": round(results["nocache"], 3),
           "cached_ms": round(results["cached"], 3),
           "cache_speedup": round(results["nocache"] / results["cached"],
                                  2),
           "hit": s.get("eager_cache_hit", 0),
           "miss": s.get("eager_cache_miss", 0),
           "uncacheable": s.get("eager_cache_uncacheable", 0),
           "hit_rate": round(s.get("eager_cache_hit", 0) / total, 3)}
    print(json.dumps(out), flush=True)
    return out


def _scan_time(fn, args, reps=30):
    """Time fn amortized inside one jit (tunnel RTT would otherwise
    dominate): scan reps iterations with a data dependency, fence with a
    device->host fetch."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def many(*args):
        def body(c, _):
            out = fn(args[0] + c, *args[1:])
            first = out[0] if isinstance(out, (tuple, list)) else out
            return c + first.mean().astype(args[0].dtype) * 0, None
        c, _ = jax.lax.scan(body, jnp.zeros((), args[0].dtype), None,
                            length=reps)
        return c

    out = many(*args)
    np.asarray(jax.device_get(out))
    t0 = time.perf_counter()
    out = many(*args)
    np.asarray(jax.device_get(out))
    return (time.perf_counter() - t0) / reps


def fused_adam_bench(n_params=85_000_000):
    """Pallas fused adam vs the XLA expression tree, GPT-2-scale tensor."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import fused_adam

    rng = np.random.default_rng(0)
    shape = (n_params // 1024, 1024)
    p = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    g = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    m = jnp.zeros(shape, jnp.float32)
    v = jnp.zeros(shape, jnp.float32)
    kw = dict(lr_t=1e-3, beta1=0.9, beta2=0.999, eps=1e-8, wd_lr=1e-4)

    t_pallas = _scan_time(
        lambda p, g, m, v: fused_adam.fused_adam_update(p, g, m, v, **kw),
        (p, g, m, v), reps=20)
    t_xla = _scan_time(
        lambda p, g, m, v: fused_adam.xla_reference(p, g, m, v, **kw),
        (p, g, m, v), reps=20)
    out = {"name": "fused_adam_85m", "pallas_ms": round(t_pallas * 1e3, 3),
           "xla_ms": round(t_xla * 1e3, 3),
           "speedup": round(t_xla / t_pallas, 3),
           "device": jax.default_backend()}
    print(json.dumps(out), flush=True)
    return out


def fused_ce_bench():
    """Pallas blockwise linear+softmax-CE vs unfused XLA, GPT-2 head shape
    (N=8192 tokens, H=1024, V=50304), fwd+bwd."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import fused_ce

    rng = np.random.default_rng(0)
    N, H, V = 8192, 1024, 50304
    h = jnp.asarray(rng.standard_normal((N, H)) * 0.02, jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((V, H)) * 0.02, jnp.bfloat16)
    lab = jnp.asarray(rng.integers(0, 50257, size=(N,)), jnp.int32)

    def g_of(fn):
        return jax.grad(lambda h, w: fn(h, w, lab).mean(), argnums=(0, 1))

    t_pallas = _scan_time(
        lambda h, w: g_of(fused_ce.fused_linear_cross_entropy)(h, w),
        (h, w), reps=20)
    t_xla = _scan_time(
        lambda h, w: g_of(fused_ce.xla_reference)(h, w), (h, w), reps=20)
    out = {"name": "fused_ce_gpt2_head",
           "pallas_ms": round(t_pallas * 1e3, 3),
           "xla_ms": round(t_xla * 1e3, 3),
           "speedup": round(t_xla / t_pallas, 3),
           "device": jax.default_backend()}
    print(json.dumps(out), flush=True)
    return out


def fused_rnn_bench(T=256, B=64, F=512, H=512):
    """The fusion_lstm question (reference operators/fused/
    fusion_lstm_op.cc): does hoisting the input projection out of the
    recurrence matter on TPU?  Times one LSTM layer fwd+bwd with the
    projection (a) pre-computed for all timesteps in one matmul (the
    shipped nn.LSTM path) vs (b) recomputed inside every scan step."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, B, F)), jnp.float32)
    w_ih = jnp.asarray(rng.standard_normal((4 * H, F)) * 0.05, jnp.float32)
    w_hh = jnp.asarray(rng.standard_normal((4 * H, H)) * 0.05, jnp.float32)
    b = jnp.zeros((4 * H,), jnp.float32)

    def cell(z, hp, cp):
        i, f, g, o = jnp.split(z, 4, axis=-1)
        cn = jax.nn.sigmoid(f) * cp + jax.nn.sigmoid(i) * jnp.tanh(g)
        return jax.nn.sigmoid(o) * jnp.tanh(cn), cn

    def lstm_fused(x, w_ih, w_hh):
        gi = x @ w_ih.T + b                          # (T, B, 4H) one matmul

        def body(carry, gi_t):
            hp, cp = carry
            hn, cn = cell(gi_t + hp @ w_hh.T, hp, cp)
            return (hn, cn), hn
        (_, _), ys = jax.lax.scan(
            body, (jnp.zeros((B, H)), jnp.zeros((B, H))), gi)
        return ys

    def lstm_naive(x, w_ih, w_hh):
        def body(carry, x_t):
            hp, cp = carry
            hn, cn = cell(x_t @ w_ih.T + b + hp @ w_hh.T, hp, cp)
            return (hn, cn), hn
        (_, _), ys = jax.lax.scan(
            body, (jnp.zeros((B, H)), jnp.zeros((B, H))), x)
        return ys

    def g_of(fn):
        return jax.grad(lambda x, wi, wh: fn(x, wi, wh).sum(),
                        argnums=(0, 1, 2))

    t_fused = _scan_time(lambda x, wi, wh: g_of(lstm_fused)(x, wi, wh),
                         (x, w_ih, w_hh), reps=10)
    t_naive = _scan_time(lambda x, wi, wh: g_of(lstm_naive)(x, wi, wh),
                         (x, w_ih, w_hh), reps=10)
    out = {"name": f"fused_lstm_T{T}_B{B}_H{H}",
           "preprojected_ms": round(t_fused * 1e3, 3),
           "inloop_ms": round(t_naive * 1e3, 3),
           "speedup": round(t_naive / t_fused, 3),
           "device": jax.default_backend()}
    print(json.dumps(out), flush=True)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--eager", action="store_true",
                    help="run the eager-vs-jit dispatch benchmark")
    ap.add_argument("--fused-adam", action="store_true",
                    help="pallas fused adam vs XLA expression tree")
    ap.add_argument("--fused-ce", action="store_true",
                    help="pallas blockwise CE vs unfused XLA")
    ap.add_argument("--fused-rnn", action="store_true",
                    help="pre-projected vs in-loop LSTM input projection")
    ap.add_argument("--eager-transformer", action="store_true",
                    help="eager dispatch cache on a transformer block "
                         "+ hit-rate counters")
    ap.add_argument("--ps-transport", action="store_true",
                    help="PS wire microbench (pull/push/push_pull per "
                         "wire dtype); gates on measured wire_mb, which "
                         "is deterministic — ms is informational")
    ap.add_argument("--zero-collectives", action="store_true",
                    help="ZeRO sharded-update collective bytes "
                         "(reduce-scatter/all-gather per wire dtype at "
                         "dp=2); gates on analytic wire_mb, which is "
                         "deterministic — ms is informational")
    ap.add_argument("--ring-collectives", action="store_true",
                    help="fused quantized ring collective bytes "
                         "(ring reduce-scatter/all-gather per wire "
                         "dtype incl. int4 at dp=2); gates on analytic "
                         "wire_mb plus the per-wire ratio ceiling vs "
                         "f32 — ms is informational")
    ap.add_argument("--parity-probe", action="store_true",
                    help="replica-parity probe overhead (dp=2 mlp1m): "
                         "armed <= 2% of step time at the default "
                         "cadence and analytic hash wire bytes "
                         "(deterministic, gated); disarmed exactly "
                         "zero probe work (in-function gate)")
    ap.add_argument("--config", help="JSON list of op configs")
    ap.add_argument("--save", help="write results JSON here")
    ap.add_argument("--compare", help="baseline JSON to gate against")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="allowed relative slowdown vs baseline")
    ap.add_argument("--thresholds",
                    help="per-op threshold JSON ({op: allowed_slowdown}, "
                         "sized from a measured run-to-run distribution — "
                         "see perf/variance_study.py); falls back to "
                         "--threshold for ops not listed")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=3,
                    help="timing passes per op; the min is reported "
                         "(tunnel-spike robustness)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="append an op_bench RunRecord (one leg per "
                         "measured metric) to the run ledger at PATH "
                         "— the perf observatory's producer hook "
                         "(suite / --ps-transport / --zero-collectives "
                         "runs)")
    a = ap.parse_args(argv)

    if a.eager:
        r = eager_vs_jit_bench(iters=a.iters if a.iters != 10 else 30)
        if a.save:
            with open(a.save, "w") as f:
                json.dump([r], f, indent=1)
        return 0
    if a.fused_adam or a.fused_ce or a.fused_rnn or a.eager_transformer:
        rs = []
        if a.fused_adam:
            rs.append(fused_adam_bench())
        if a.fused_ce:
            rs.append(fused_ce_bench())
        if a.fused_rnn:
            rs.append(fused_rnn_bench())
        if a.eager_transformer:
            rs.append(eager_transformer_bench())
        if a.save:
            with open(a.save, "w") as f:
                json.dump(rs, f, indent=1)
        return 0

    if a.ps_transport:
        suite = PS_TRANSPORT_SUITE
        results = ps_transport_bench(repeats=a.repeats)
    elif a.zero_collectives:
        suite = ZERO_COLLECTIVES_SUITE
        results = zero_collectives_bench(repeats=a.repeats)
    elif a.ring_collectives:
        suite = RING_COLLECTIVES_SUITE
        results = ring_collectives_bench(repeats=a.repeats)
    elif a.parity_probe:
        suite = PARITY_PROBE_SUITE
        results = parity_probe_bench(repeats=a.repeats)
    else:
        suite = BUILTIN_SUITE
        if a.config:
            with open(a.config) as f:
                suite = json.load(f)
        results = []
        for cfg in suite:
            try:
                r = run_one(cfg, iters=a.iters, repeats=a.repeats)
            except Exception as e:           # noqa: BLE001
                r = {"name": cfg.get("name", cfg.get("op")),
                     "error": repr(e)}
            results.append(r)
            print(json.dumps(r), flush=True)

    if a.save:
        with open(a.save, "w") as f:
            json.dump(results, f, indent=1)
    if a.ledger:
        # ms per op plus wire_mb where measured; RunLedger.append never
        # raises, so the gate below still runs on a broken ledger disk.
        # Label per suite VARIANT and skip the registry snapshot (the
        # bench.py discipline): the legs are the cross-run series, and
        # a process-cumulative counter snapshot would differ wildly
        # between variants sharing one ledger — a self-flagged
        # "regression" on a healthy machine
        from paddle_tpu.framework import runlog
        variant = "ps_transport" if a.ps_transport else \
            "zero_collectives" if a.zero_collectives else \
            "ring_collectives" if a.ring_collectives else \
            "parity_probe" if a.parity_probe else "suite"
        legs = []
        for r in results:
            if "ms" in r:
                legs.append({"metric": f"{r['name']}_ms",
                             "value": r["ms"], "unit": "ms"})
            if "wire_mb" in r:
                legs.append({"metric": f"{r['name']}_wire_mb",
                             "value": r["wire_mb"], "unit": "MB"})
        runlog.RunLedger(a.ledger).append(
            runlog.capture("op_bench", label=variant, legs=legs,
                           include_snapshot=False))
    if a.compare:
        with open(a.compare) as f:
            base = {r["name"]: r for r in json.load(f) if "ms" in r}
        # transport/parity entries gate on wire_mb or a plain wall
        # clock (no scan estimator involved)
        stale = [n for n, r in base.items()
                 if "scan_len" not in r and "wire_mb" not in r
                 and not n.startswith("parity_probe_")]
        if stale:
            print(f"baseline {a.compare} predates the scan-difference "
                  f"estimator (entries without scan_len: {stale}); "
                  "re-record it with --save on this hardware — comparing "
                  "across estimators would gate nothing", file=sys.stderr)
            return 2
        # key validation up front: a baseline/thresholds file whose keys
        # drift from the registered suite must fail with a NAMED diff,
        # not silently skip ops out of the gate (a gate that compares
        # nothing is a false green).  Threshold keys may name any
        # registered op (builtin or current suite) so one measured
        # thresholds file serves subset runs; baseline must cover every
        # op this run gates.
        suite_names = {c.get("name", c.get("op")) for c in suite}
        known = suite_names | {c["name"] for c in BUILTIN_SUITE} \
            | {c["name"] for c in PS_TRANSPORT_SUITE} \
            | {c["name"] for c in ZERO_COLLECTIVES_SUITE} \
            | {c["name"] for c in RING_COLLECTIVES_SUITE} \
            | {c["name"] for c in PARITY_PROBE_SUITE}
        missing_base = sorted(suite_names - set(base))
        if missing_base:
            print(f"baseline {a.compare} has no entry for suite op(s): "
                  f"{missing_base} (baseline keys: {sorted(base)}) — "
                  "the gate would silently skip them; re-record with "
                  "--save or trim the suite", file=sys.stderr)
            return 2
        per_op = {}
        if a.thresholds:
            with open(a.thresholds) as f:
                per_op = json.load(f)
            unknown_thr = sorted(set(per_op) - known)
            if unknown_thr:
                print(f"thresholds {a.thresholds} names unregistered "
                      f"op(s): {unknown_thr} (registered: "
                      f"{sorted(known)}) — a typo'd key silently falls "
                      "back to --threshold; fix the key or remove it",
                      file=sys.stderr)
                return 2
        # a current run that refused/failed to measure an op the
        # baseline covers is the same false green the key validation
        # above guards against: the op leaves the gate with no signal
        ungated = sorted(r.get("name") for r in results
                         if "ms" not in r and r.get("name") in base)
        if ungated:
            print(f"current run produced no timing for baselined "
                  f"op(s): {ungated} — the gate cannot compare them "
                  "(see the per-op error records above); fix the "
                  "measurement or trim the suite", file=sys.stderr)
            return 2
        failed = []
        for r in results:
            b = base.get(r.get("name"))
            if b is None or "ms" not in r:
                continue
            if b.get("device") and r.get("device") and \
                    b["device"] != r["device"]:
                print(f"SKIP {r['name']}: baseline device "
                      f"{b['device']!r} != current {r['device']!r}",
                      file=sys.stderr)
                continue
            if b["ms"] <= 0:
                # a zero/negative baseline (recorded by a pre-guard
                # version) gates nothing and would ZeroDivisionError
                print(f"SKIP {r['name']}: baseline ms {b['ms']!r} <= 0 — "
                      "re-record the baseline with --save",
                      file=sys.stderr)
                continue
            thr = float(per_op.get(r["name"], a.threshold))
            # transport records gate on measured wire bytes (exact,
            # deterministic — "hold the line on transport bytes"); op
            # timings gate on the scan-difference ms as before
            if "wire_mb" in b and "wire_mb" in r:
                metric, unit = "wire_mb", "MB"
                if b["wire_mb"] <= 0:
                    print(f"SKIP {r['name']}: baseline wire_mb "
                          f"{b['wire_mb']!r} <= 0 — re-record",
                          file=sys.stderr)
                    continue
            else:
                metric, unit = "ms", "ms"
            slowdown = r[metric] / b[metric] - 1.0
            if slowdown > thr:
                failed.append((r["name"], b[metric], r[metric], slowdown,
                               thr, unit))
        for name, bms, rms, s, thr, unit in failed:
            print(f"REGRESSION {name}: {bms}{unit} -> {rms}{unit} "
                  f"(+{s:.0%}, allowed +{thr:.0%})", file=sys.stderr)
        if failed:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
