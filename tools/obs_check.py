#!/usr/bin/env python
"""CI observability lane.

End-to-end check of the tracing + metrics plane on a real (tiny) train:

1. arm the process tracer, run a 3-step mini train (TrainStep emits a
   ``train.step`` span per step);
2. merge the span file(s) with tools/trace_merge.py and validate the
   chrome-trace schema;
3. render ``monitor.export_prometheus()`` and validate it against the
   Prometheus text-format grammar (plus histogram invariants).

Exits non-zero on any violation.  Deterministic, CPU-only, seconds.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import trace_merge  # noqa: E402
from paddle_tpu.framework import monitor  # noqa: E402
from paddle_tpu.framework.observability import (  # noqa: E402
    tracer, validate_prometheus)
from paddle_tpu.jit import TrainStep  # noqa: E402

STEPS = 3


def mini_train(n_steps: int = STEPS):
    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 2)).astype(np.float32))
    return [float(step(x, y)) for _ in range(n_steps)]


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        # -- 1. traced mini train ------------------------------------------
        tracer.enable(os.path.join(d, "traces"), label="trainer")
        losses = mini_train()
        assert all(np.isfinite(losses)), f"mini train diverged: {losses}"
        span_file = tracer.path()
        tracer.disable()
        assert os.path.exists(span_file), "tracer wrote no span file"

        # -- 2. merge + chrome-trace schema --------------------------------
        merged_path = os.path.join(d, "merged.json")
        rc = trace_merge.main(["--dir", os.path.join(d, "traces"),
                               "--out", merged_path])
        assert rc == 0, "trace_merge failed"
        with open(merged_path) as f:
            trace = json.load(f)
        n_spans = trace_merge.validate_chrome_trace(trace)
        names = [e["name"] for e in trace["traceEvents"]
                 if e["ph"] == "X"]
        assert names.count("train.step") >= STEPS, \
            f"expected >= {STEPS} train.step spans, got {names}"
        print(f"obs_check: chrome trace OK ({n_spans} spans, "
              f"{names.count('train.step')} train.step)")

        # -- 3. prometheus export grammar ----------------------------------
        text = monitor.export_prometheus()
        n_samples = validate_prometheus(text)
        assert "train_steps_total" in text, "steps counter not exported"
        assert "train_step_ms_bucket" in text, \
            "step-time histogram not exported"
        print(f"obs_check: prometheus export OK ({n_samples} samples)")
    print("obs_check: PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
