#!/usr/bin/env python
"""CI observability lane.

End-to-end check of the tracing + metrics plane on a real (tiny) train:

1. arm the process tracer, run a 3-step mini train (TrainStep emits a
   ``train.step`` span per step) AND drain a small ingest pipeline
   (io/pipeline.py emits a span per stage: ``ingest.decode``,
   ``ingest.transfer``, ``ingest.wait``);
2. merge the span file(s) with tools/trace_merge.py and validate the
   chrome-trace schema — train-step and ingest-stage spans must both
   appear in the merged trace;
3. render ``monitor.export_prometheus()`` and validate it against the
   Prometheus text-format grammar (plus histogram invariants) —
   ``input_stall_pct``, the per-stage ingest histograms, and the cache
   hit/miss counters must all export.

Exits non-zero on any violation.  Deterministic, CPU-only, seconds.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import trace_merge  # noqa: E402
from paddle_tpu.framework import monitor  # noqa: E402
from paddle_tpu.framework.observability import (  # noqa: E402
    tracer, validate_prometheus)
from paddle_tpu.jit import TrainStep  # noqa: E402

STEPS = 3


def mini_train(n_steps: int = STEPS):
    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 2)).astype(np.float32))
    return [float(step(x, y)) for _ in range(n_steps)]


INGEST_SPANS = ("ingest.decode", "ingest.transfer", "ingest.wait")
INGEST_METRICS = ("input_stall_pct", "ingest_decode_ms_bucket",
                  "ingest_collate_ms_bucket", "ingest_transfer_ms_bucket",
                  "ingest_wait_ms_bucket", "ingest_cache_hits_total",
                  "ingest_cache_misses_total")


def mini_ingest():
    """Two epochs of a cached, pipelined ingest drain — one pass to
    record the sample cache, one to hit it, so the hit AND miss
    counters both export."""
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.io.pipeline import (CachedDataset, IngestPipeline,
                                        SampleCache)
    rng = np.random.default_rng(0)
    ds = TensorDataset([paddle.to_tensor(
        rng.standard_normal((16, 4)).astype(np.float32))])
    cds = CachedDataset(ds, SampleCache(mode="memory",
                                        max_bytes=1 << 20))
    n = 0
    for _ in range(2):
        pipe = IngestPipeline(DataLoader(cds, batch_size=4),
                              prefetch_depth=1)
        n += sum(1 for _ in pipe)
    return n


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        # -- 1. traced mini train + ingest drain ---------------------------
        tracer.enable(os.path.join(d, "traces"), label="trainer")
        losses = mini_train()
        assert all(np.isfinite(losses)), f"mini train diverged: {losses}"
        n_batches = mini_ingest()
        assert n_batches == 8, f"ingest drain short: {n_batches}"
        span_file = tracer.path()
        tracer.disable()
        assert os.path.exists(span_file), "tracer wrote no span file"

        # -- 2. merge + chrome-trace schema --------------------------------
        merged_path = os.path.join(d, "merged.json")
        rc = trace_merge.main(["--dir", os.path.join(d, "traces"),
                               "--out", merged_path])
        assert rc == 0, "trace_merge failed"
        with open(merged_path) as f:
            trace = json.load(f)
        n_spans = trace_merge.validate_chrome_trace(trace)
        names = [e["name"] for e in trace["traceEvents"]
                 if e["ph"] == "X"]
        assert names.count("train.step") >= STEPS, \
            f"expected >= {STEPS} train.step spans, got {names}"
        for span in INGEST_SPANS:
            assert span in names, \
                f"ingest stage span {span!r} missing from merged trace"
        print(f"obs_check: chrome trace OK ({n_spans} spans, "
              f"{names.count('train.step')} train.step, "
              f"{sum(names.count(s) for s in INGEST_SPANS)} ingest.*)")

        # -- 3. prometheus export grammar ----------------------------------
        text = monitor.export_prometheus()
        n_samples = validate_prometheus(text)
        assert "train_steps_total" in text, "steps counter not exported"
        assert "train_step_ms_bucket" in text, \
            "step-time histogram not exported"
        for metric in INGEST_METRICS:
            assert metric in text, f"{metric} not exported"
        print(f"obs_check: prometheus export OK ({n_samples} samples, "
              f"ingest metrics present)")
    print("obs_check: PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
