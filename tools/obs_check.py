#!/usr/bin/env python
"""CI observability lane.

End-to-end check of the tracing + metrics plane on a real (tiny) train:

1. arm the process tracer, run a 3-step mini train (TrainStep emits a
   ``train.step`` span per step) AND drain a small ingest pipeline
   (io/pipeline.py emits a span per stage: ``ingest.decode``,
   ``ingest.transfer``, ``ingest.wait``);
2. merge the span file(s) with tools/trace_merge.py and validate the
   chrome-trace schema — train-step and ingest-stage spans must both
   appear in the merged trace;
3. render ``monitor.export_prometheus()`` and validate it against the
   Prometheus text-format grammar (plus histogram invariants and the
   ``# HELP``-per-metric scraper contract) — ``input_stall_pct``, the
   per-stage ingest histograms, and the cache hit/miss counters must
   all export;
4. **collector leg** (framework/collector.py): (a) with
   ``collector.rpc`` error faults injected on EVERY push, a training
   loop pushing telemetry must produce a bit-identical loss trajectory
   to a collector-less run — drops counted, nothing blocks; (b) a mini
   cluster (2 workers + 1 PS server + collector, one rank with
   injected per-step latency) must name exactly that rank in the
   collector's straggler report, in the ``cluster_top`` view (schema-
   validated), and in the cluster-level run-ledger record that
   ``perf_report compare`` consumes.

Exits non-zero on any violation.  Deterministic, CPU-only, seconds.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn as nn  # noqa: E402
import trace_merge  # noqa: E402
from paddle_tpu.framework import monitor  # noqa: E402
from paddle_tpu.framework.observability import (  # noqa: E402
    tracer, validate_prometheus)
from paddle_tpu.jit import TrainStep  # noqa: E402

STEPS = 3


def mini_train(n_steps: int = STEPS):
    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 2)).astype(np.float32))
    return [float(step(x, y)) for _ in range(n_steps)]


INGEST_SPANS = ("ingest.decode", "ingest.transfer", "ingest.wait")
INGEST_METRICS = ("input_stall_pct", "ingest_decode_ms_bucket",
                  "ingest_collate_ms_bucket", "ingest_transfer_ms_bucket",
                  "ingest_wait_ms_bucket", "ingest_cache_hits_total",
                  "ingest_cache_misses_total")


def mini_ingest():
    """Two epochs of a cached, pipelined ingest drain — one pass to
    record the sample cache, one to hit it, so the hit AND miss
    counters both export."""
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.io.pipeline import (CachedDataset, IngestPipeline,
                                        SampleCache)
    rng = np.random.default_rng(0)
    ds = TensorDataset([paddle.to_tensor(
        rng.standard_normal((16, 4)).astype(np.float32))])
    cds = CachedDataset(ds, SampleCache(mode="memory",
                                        max_bytes=1 << 20))
    n = 0
    for _ in range(2):
        pipe = IngestPipeline(DataLoader(cds, batch_size=4),
                              prefetch_depth=1)
        n += sum(1 for _ in pipe)
    return n


def _collector_train(n_steps: int, client=None):
    """Fixed-seed training loop, optionally pushing telemetry after
    every step — the bit-identical-under-faults gate's subject."""
    from paddle_tpu.framework import collector as collector_mod
    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((8, 2)).astype(np.float32))
    losses = []
    for _ in range(n_steps):
        losses.append(float(step(x, y)))
        if client is not None:
            client.push(collector_mod.local_payload())
    return losses


def collector_leg(d: str):
    """The cluster-telemetry gates (see module docstring item 4)."""
    import time

    import cluster_top
    from paddle_tpu.framework import chaos, runlog
    from paddle_tpu.framework.collector import (CollectorClient,
                                                CollectorServer)

    # -- 4a. collector loss is invisible to training --------------------
    baseline = _collector_train(5)
    srv = CollectorServer().start()
    chaos.reset()
    chaos.arm("collector.rpc", mode="error", every=1)
    try:
        cli = CollectorClient(srv.endpoint, worker="gate", timeout=1.0)
        faulted = _collector_train(5, client=cli)
        cli.stop()
    finally:
        chaos.disarm("collector.rpc")
        srv.shutdown()
    assert faulted == baseline, \
        f"trajectory diverged under collector faults: {faulted} " \
        f"vs {baseline}"
    assert cli.dropped == 5 and cli.sent == 0, \
        f"expected every push dropped: sent={cli.sent} " \
        f"dropped={cli.dropped}"
    print(f"obs_check: collector chaos OK (trajectory bit-identical, "
          f"{cli.dropped} pushes dropped, none blocked)")

    # -- 4b. mini cluster: straggler named everywhere -------------------
    from paddle_tpu.distributed.ps import HostEmbeddingTable
    from paddle_tpu.distributed.ps.service import PsClient, PsServer
    from paddle_tpu.framework.flags import set_flags

    # hot-row telemetry is opt-in (per-pull cost); this leg gates it ON
    set_flags({"ps_hot_row_k": 32})
    ledger_path = os.path.join(d, "cluster_ledger.jsonl")
    col = CollectorServer(straggler_ratio=2.0, window=4,
                          ledger_path=ledger_path).start()
    table = HostEmbeddingTable(64, 8, optimizer="sgd", seed=0)
    ps = PsServer({"emb": table}, port=0).start()
    K = 8
    cli = ps_cli = None
    workers = {}
    try:
        # the PS shard pushes its per-table telemetry like serve() does
        ps_cli = CollectorClient(col.endpoint, worker="server-0",
                                 role="server", timeout=1.0)
        rng = np.random.default_rng(0)
        for name, extra_ms in (("trainer-0", 0.0), ("trainer-1", 30.0)):
            workers[name] = {"client": CollectorClient(
                col.endpoint, worker=name, role="trainer", timeout=1.0),
                "count": 0, "sum": 0.0, "extra": extra_ms}
        cli = PsClient([f"127.0.0.1:{ps.port}"], wire_dtype="f32",
                       backoff_base=0.01)
        for step_i in range(K):
            for name, st in workers.items():
                t0 = time.perf_counter()
                cli.pull("emb", rng.integers(0, 64, size=(8,)))
                if st["extra"]:
                    time.sleep(st["extra"] / 1e3)  # the injected latency
                ms = (time.perf_counter() - t0) * 1e3
                st["count"] += 1
                st["sum"] += ms
                st["client"].push({"stats": {}, "hists": {
                    "train_step_ms": {"count": st["count"],
                                      "sum": st["sum"],
                                      "p50": ms, "p99": ms}}})
            ps_cli.push({"stats": {}, "hists": {},
                         "tables": ps.table_telemetry()})
        deadline = time.time() + 10
        while time.time() < deadline:
            if col.straggler_report()["stragglers"] == ["trainer-1"]:
                break
            time.sleep(0.05)
        report = col.straggler_report()
        assert report["stragglers"] == ["trainer-1"], \
            f"straggler not named within {K} steps: {report}"
        assert report["scores"]["trainer-0"] < 2.0, \
            f"clean rank flagged: {report}"
        # the live view (what cluster_top renders) must pass the schema
        view = cluster_top.fetch_view(col.endpoint)
        n_workers = cluster_top.validate_view(view)
        assert n_workers == 3, f"expected 3 reporting processes: {view}"
        assert view["stragglers"] == ["trainer-1"]
        assert view["tables"].get("emb", {}).get("pulls", 0) > 0, \
            f"PS table telemetry missing: {view['tables']}"
        assert view["tables"]["emb"].get("hot_rows"), \
            "hot-row sketch empty in the cluster view"
        text = cluster_top.render(view)
        assert "trainer-1" in text and "YES" in text
        # the cluster-level ledger record perf_report compare consumes
        rec, committed = col.capture_record(label="obs_check")
        assert committed, "cluster RunRecord did not commit"
        assert rec["cluster"]["stragglers"] == ["trainer-1"]
        assert rec["summary"]["cluster_straggler_count"] == 1
        assert rec["summary"]["cluster_step_skew"] >= 2.0
        stored = runlog.RunLedger(ledger_path).records(kind="cluster")
        assert stored and \
            stored[-1]["cluster"]["stragglers"] == ["trainer-1"]
        import perf_report
        series = perf_report.build_series(stored * 2)
        assert "cluster_step_skew" in series and \
            "cluster_straggler_count" in series, sorted(series)
        print(f"obs_check: collector cluster OK (straggler trainer-1 "
              f"named in report/view/ledger, score "
              f"{report['scores']['trainer-1']:.2f}, emb pulls "
              f"{view['tables']['emb']['pulls']})")
    finally:
        try:
            if cli is not None:
                cli.bye()
        finally:
            for st in workers.values():
                st["client"].stop()
            if ps_cli is not None:
                ps_cli.stop()
            ps.shutdown()
            col.shutdown()


def main() -> int:
    with tempfile.TemporaryDirectory() as d:
        # -- 1. traced mini train + ingest drain ---------------------------
        tracer.enable(os.path.join(d, "traces"), label="trainer")
        losses = mini_train()
        assert all(np.isfinite(losses)), f"mini train diverged: {losses}"
        n_batches = mini_ingest()
        assert n_batches == 8, f"ingest drain short: {n_batches}"
        span_file = tracer.path()
        tracer.disable()
        assert os.path.exists(span_file), "tracer wrote no span file"

        # -- 2. merge + chrome-trace schema --------------------------------
        merged_path = os.path.join(d, "merged.json")
        rc = trace_merge.main(["--dir", os.path.join(d, "traces"),
                               "--out", merged_path])
        assert rc == 0, "trace_merge failed"
        with open(merged_path) as f:
            trace = json.load(f)
        n_spans = trace_merge.validate_chrome_trace(trace)
        names = [e["name"] for e in trace["traceEvents"]
                 if e["ph"] == "X"]
        assert names.count("train.step") >= STEPS, \
            f"expected >= {STEPS} train.step spans, got {names}"
        for span in INGEST_SPANS:
            assert span in names, \
                f"ingest stage span {span!r} missing from merged trace"
        print(f"obs_check: chrome trace OK ({n_spans} spans, "
              f"{names.count('train.step')} train.step, "
              f"{sum(names.count(s) for s in INGEST_SPANS)} ingest.*)")

        # -- 3. prometheus export grammar ----------------------------------
        # require_help: every metric must carry its # HELP line — the
        # full contract a real Prometheus scraper expects
        text = monitor.export_prometheus()
        n_samples = validate_prometheus(text, require_help=True)
        assert "train_steps_total" in text, "steps counter not exported"
        assert "train_step_ms_bucket" in text, \
            "step-time histogram not exported"
        assert "# HELP train_steps_total" in text, "HELP line missing"
        for metric in INGEST_METRICS:
            assert metric in text, f"{metric} not exported"
        print(f"obs_check: prometheus export OK ({n_samples} samples, "
              f"HELP lines present, ingest metrics present)")

        # -- 4. cluster telemetry collector --------------------------------
        collector_leg(d)
    print("obs_check: PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
