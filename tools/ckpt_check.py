#!/usr/bin/env python
"""Offline checkpoint fsck — verify / list / gc over the durable-state
layout, no training session required.

Works on either shape the repo writes:

* a SINGLE checkpoint directory (``metadata.json`` + shards, optional
  ``COMMIT``) — e.g. one TrainEpochRange slot;
* a GENERATION ROOT of ``gen_<NNNNNNNN>`` directories
  (``distributed/durable.py`` CheckpointManager layout).

Subcommands::

    # re-read every shard against its crc32 stamp; exit 1 on corruption,
    # naming each bad file
    python tools/ckpt_check.py verify <dir> [--shallow] [--json]

    # one line per generation/slot: committed? verified? step, bytes
    python tools/ckpt_check.py list <root> [--json]

    # apply the retention policy offline (FLAGS_ckpt_keep_last /
    # _keep_every, or --keep-last/--keep-every); --dry-run prints only
    python tools/ckpt_check.py gc <root> [--keep-last K] [--keep-every N]
        [--dry-run] [--json]

Exit status: 0 clean, 1 corruption found (verify: any problem; list: no
verifiable checkpoint), 2 usage/IO errors.  ``--json`` emits one
machine-readable report on stdout — the ci.sh durability lane greps it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed import checkpoint  # noqa: E402
from paddle_tpu.distributed.durable import (  # noqa: E402
    CheckpointManager, generation_dirs)


def _is_single_checkpoint(path: str) -> bool:
    return os.path.exists(os.path.join(path, "metadata.json"))


def _targets(path: str):
    """(label, dirpath) pairs: the dir itself, or its generations."""
    if _is_single_checkpoint(path):
        return [(os.path.basename(path.rstrip(os.sep)) or path, path)]
    gens = generation_dirs(path)
    if gens:
        return [(f"gen_{g:08d}", d) for g, d in gens]
    # two-slot TrainEpochRange root: verify whatever slots exist
    return [(n, os.path.join(path, n)) for n in ("slot0", "slot1")
            if os.path.isdir(os.path.join(path, n))]


def _dir_bytes(dirpath: str) -> int:
    total = 0
    try:
        for name in os.listdir(dirpath):
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    except OSError:
        pass
    return total


def _describe(label: str, dirpath: str, deep: bool) -> dict:
    problems = checkpoint.verify_checkpoint(dirpath, deep=deep)
    meta_step = None
    try:
        meta_step = checkpoint.checkpoint_meta(dirpath).get("step")
    except (OSError, ValueError):
        pass
    return {"name": label, "dir": dirpath, "step": meta_step,
            "committed": checkpoint.is_committed(dirpath),
            "verified": not problems, "problems": problems,
            "bytes": _dir_bytes(dirpath)}


def cmd_verify(args) -> int:
    targets = _targets(args.path)
    if not targets:
        print(f"ckpt_check: no checkpoint found under {args.path}",
              file=sys.stderr)
        return 2
    report = [_describe(label, d, deep=not args.shallow)
              for label, d in targets]
    corrupt = [r for r in report if r["problems"]]
    if args.json:
        print(json.dumps({"cmd": "verify", "path": args.path,
                          "checkpoints": report,
                          "corrupt": len(corrupt)}, indent=2))
    else:
        for r in report:
            verdict = "OK" if r["verified"] else "CORRUPT"
            commit = "committed" if r["committed"] else "uncommitted"
            print(f"{verdict:8s} {r['name']}  step={r['step']}  "
                  f"{commit}  {r['bytes']} bytes")
            for p in r["problems"]:
                print(f"         {p['file']}: {p['reason']}")
    return 1 if corrupt else 0


def cmd_list(args) -> int:
    targets = _targets(args.path)
    report = [_describe(label, d, deep=False) for label, d in targets]
    newest = None
    for r in reversed(report):
        if r["committed"] and r["verified"]:
            newest = r["name"]
            break
    if args.json:
        print(json.dumps({"cmd": "list", "path": args.path,
                          "checkpoints": report,
                          "newest_verified": newest}, indent=2))
    else:
        for r in report:
            mark = "*" if r["name"] == newest else " "
            print(f"{mark} {r['name']}  step={r['step']}  "
                  f"committed={r['committed']}  verified={r['verified']}  "
                  f"{r['bytes']} bytes")
        print(f"newest verified: {newest}")
    return 0 if newest is not None else 1


def cmd_gc(args) -> int:
    mgr = CheckpointManager(args.path, keep_last=args.keep_last,
                            keep_every=args.keep_every)
    before = mgr.generations()
    if args.dry_run:
        newest = mgr.latest_verified(deep=True)
        keep = set(before[-mgr.keep_last:])
        if newest is not None:
            keep.add(newest)
        if mgr.keep_every > 0:
            keep.update(g for g in before if g % mgr.keep_every == 0)
        deleted = [] if newest is None else \
            [g for g in before if g not in keep and g < newest]
    else:
        deleted = mgr.gc()
    out = {"cmd": "gc", "path": args.path, "generations": before,
           "deleted": deleted, "dry_run": bool(args.dry_run),
           "kept": [g for g in before if g not in deleted]}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"generations: {before}")
        print(f"{'would delete' if args.dry_run else 'deleted'}: {deleted}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tools/ckpt_check.py", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    v = sub.add_parser("verify", help="re-read shards against crc stamps")
    v.add_argument("path")
    v.add_argument("--shallow", action="store_true",
                   help="existence+size only (skip the crc re-read)")
    v.add_argument("--json", action="store_true")

    li = sub.add_parser("list", help="enumerate generations/slots")
    li.add_argument("path")
    li.add_argument("--json", action="store_true")

    g = sub.add_parser("gc", help="apply the retention policy offline")
    g.add_argument("path")
    g.add_argument("--keep-last", type=int, default=None)
    g.add_argument("--keep-every", type=int, default=None)
    g.add_argument("--dry-run", action="store_true")
    g.add_argument("--json", action="store_true")

    args = p.parse_args(argv)
    try:
        return {"verify": cmd_verify, "list": cmd_list,
                "gc": cmd_gc}[args.cmd](args)
    except OSError as e:
        print(f"ckpt_check: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
