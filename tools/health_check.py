#!/usr/bin/env python
"""Health report CLI — the perf health plane's decision surface.

Renders one report (text or JSON) from a metrics snapshot and/or a
trace directory, and exits nonzero when a gate trips — so CI, a
launcher wrapper, and the future autotuner all consume the same
verdict the detectors produce:

* **anomalies** — ``health_anomalies_total`` (+ the per-signal
  ``health_anomaly_<signal>_total`` split) from the streaming
  detectors (framework/health.py);
* **compiles** — ``jit_compiles_total`` / ``jit_cache_hits_total`` /
  per-cause counters, the ``compile_ms`` histogram, and the
  steady-state recompile count the compile-storm detector feeds;
* **memory** — ``device_mem_live_bytes`` / ``device_mem_peak_bytes``
  and the per-tag attribution gauges;
* **numerics** — the model-numerics plane (framework/numerics.py):
  global grad/param norms, update ratio, max-abs grad, non-finite
  step + NaN-skip counts, grad-norm detector anomalies, the sampled
  per-leaf grad norms, and (mini-train ``--nan-step``) the NaN
  provenance verdict;
* **spans** — the per-span-name aggregate table
  (``tools/trace_merge.py summarize``) over ``--trace-dir``.

Inputs:

* ``--metrics FILE`` — a ``monitor.snapshot()`` JSON file, or a
  Prometheus text rendering (``MetricsReporter`` output; gauges and
  ``_total`` counters are read, histogram summaries need the JSON
  form);
* ``--trace-dir DIR`` — per-process ``trace_*.jsonl`` span files;
* ``--mini-train N`` — self-contained mode: run a traced N-step mini
  train with the default detectors armed, snapshot, and evaluate
  in-process (the CI health lane; no files needed).

Gates (any trip → exit 1): ``--max-anomalies`` (default 0),
``--max-steady-recompiles`` (default 0), ``--max-input-stall``
(percent; off by default), ``--max-grad-anomalies`` (grad-norm
detector trips; off by default), ``--max-blame category=pct``
(repeatable; blame-share ceiling per causal category from
``framework/blame.py`` — requires a trace), and — implicit with
``--nan-step`` — the NaN-provenance verdict (the seeded fault must be
attributed to the poisoned leaf).

Usage::

    python tools/health_check.py --mini-train 30
    python tools/health_check.py --mini-train 30 --numerics \\
        --max-grad-anomalies 0
    python tools/health_check.py --mini-train 30 --nan-step 20
    python tools/health_check.py --metrics snap.json --trace-dir /tmp/tr
    python tools/health_check.py --metrics metrics.prom --format json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the --zero leg shards over a dp=2 mesh of CPU virtual devices; the
# flag only takes effect if it lands before jax's backend initializes
# (set here, at import, because the paddle import chain pulls jax in
# during argument validation — a no-op for non-CPU backends and for
# embedders that already initialized jax)
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

__all__ = ["load_metrics", "build_report", "evaluate_gates",
           "parse_max_blame", "format_report", "mini_train",
           "mini_train_ps", "mini_train_zero", "build_incident_step",
           "main"]


# ---------------------------------------------------------------------------
# the two-branch numerics net — module-level so the postmortem plane's
# replay (tools/replay.py) can rebuild the exact step surface the
# mini-train recorded an incident on
# ---------------------------------------------------------------------------

def _two_branch_net():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    class _TwoBranch(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 4)
            self.aux_w = self.create_parameter(
                [4], default_initializer=paddle.nn.initializer
                .Constant(0.1))

        def forward(self, x, z):
            return self.fc(x), (self.aux_w * z).sum()

    return _TwoBranch()


def _two_branch_loss(m, x, z, y):
    out, aux = m(x, z)
    return ((out - y) ** 2).mean() + 1e-3 * aux


def build_incident_step(seed: int = 0, lr: float = 0.05,
                        max_consecutive_bad: int = 3):
    """Replay builder (``incident.set_program`` ref
    ``"health_check:build_incident_step"``): the resilient-wrapped
    two-branch numerics step the mini-train records incidents on.
    Registers itself as this process's program descriptor, so any
    bundle captured off the returned step replays standalone."""
    import paddle_tpu as paddle
    from paddle_tpu.framework import incident
    from paddle_tpu.framework.resilient import ResilientTrainStep
    from paddle_tpu.jit import TrainStep
    paddle.seed(int(seed))
    net = _two_branch_net()
    opt = paddle.optimizer.SGD(learning_rate=float(lr),
                               parameters=net.parameters())
    incident.set_program("health_check:build_incident_step", seed=int(seed),
                         lr=float(lr),
                         max_consecutive_bad=int(max_consecutive_bad))
    return ResilientTrainStep(TrainStep(net, _two_branch_loss, opt),
                              max_consecutive_bad=int(max_consecutive_bad))


# ---------------------------------------------------------------------------
# input loading
# ---------------------------------------------------------------------------

def _parse_prometheus(text: str) -> dict:
    """Reduce a Prometheus text rendering to the snapshot shape: plain
    samples become stats; histogram ``_sum``/``_count`` pairs become
    minimal histogram records (no percentiles — the JSON snapshot form
    carries those)."""
    stats = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" in line:
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        try:
            stats[parts[0]] = float(parts[1])
        except ValueError:
            continue
    hists = {}
    for name, v in list(stats.items()):
        if name.endswith("_count") and name[:-len("_count")] + "_sum" \
                in stats:
            base = name[:-len("_count")]
            count = int(v)
            total = stats[base + "_sum"]
            hists[base] = {"count": count, "sum": total,
                           "mean": total / count if count else 0.0}
    return {"stats": stats, "histograms": hists}


def load_metrics(path: str) -> dict:
    """Load a metrics snapshot: ``monitor.snapshot()`` JSON or a
    Prometheus text file (sniffed by the leading character)."""
    with open(path) as f:
        text = f.read()
    if text.lstrip().startswith("{"):
        snap = json.loads(text)
        snap.setdefault("stats", {})
        snap.setdefault("histograms", {})
        return snap
    return _parse_prometheus(text)


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def _tail_spans(spans: list, n: int, step_span: str = "train.step"):
    """Spans from the last ``n`` train steps only: everything starting
    at or after the n-th-from-last step span's start.  The autopilot
    lane's recovery gate reads blame over the TAIL — an injected storm
    the controller fixed mid-run must not dominate the verdict through
    the cumulative average."""
    steps = sorted((s for s in spans if s.get("name") == step_span),
                   key=lambda s: s["ts"])
    if n <= 0 or len(steps) <= n:
        return spans
    t0 = steps[-n]["ts"]
    return [s for s in spans if s["ts"] >= t0]


def build_report(snap: dict, trace_dir: Optional[str] = None,
                 health_snapshot: Optional[dict] = None,
                 blame_tail: Optional[int] = None,
                 step_span: str = "train.step") -> dict:
    """Fold a metrics snapshot (+ optional trace dir and live health
    state) into the report dict the gates and renderers consume.
    ``blame_tail=N`` computes blame over only the last N steps' spans
    (see :func:`_tail_spans`); ``step_span`` names the per-step span
    blame anchors on (``zero.step`` for the ZeRO leg)."""
    stats = snap.get("stats", {})
    hists = snap.get("histograms", {})

    anomalies = {k: int(v) for k, v in stats.items()
                 if k.startswith("health_anomaly_") and k.endswith("_total")}
    compiles = {
        "jit_compiles_total": int(stats.get("jit_compiles_total", 0)),
        "jit_cache_hits_total": int(stats.get("jit_cache_hits_total", 0)),
        "jit_recompiles_steady_total": int(
            stats.get("jit_recompiles_steady_total", 0)),
        "by_cause": {k[len("jit_compiles_"):-len("_total")]: int(v)
                     for k, v in stats.items()
                     if k.startswith("jit_compiles_") and
                     k.endswith("_total") and k != "jit_compiles_total"},
        "compile_ms": hists.get("compile_ms"),
    }
    memory = {
        "live_bytes": int(stats.get("device_mem_live_bytes", 0)),
        "peak_bytes": int(stats.get("device_mem_peak_bytes", 0)),
        "tags": {k[len("device_mem_"):-len("_bytes")]: int(v)
                 for k, v in stats.items()
                 if k.startswith("device_mem_") and k.endswith("_bytes")
                 and k not in ("device_mem_live_bytes",
                               "device_mem_peak_bytes")},
    }
    def _leaf_split(k, prefix):
        # per-leaf numerics gauges: "numerics_grad_norm[fc.weight]"
        return k[len(prefix) + 1:-1] if k.startswith(prefix + "[") \
            and k.endswith("]") else None

    numerics = {
        "grad_norm": stats.get("numerics_grad_norm"),
        "param_norm": stats.get("numerics_param_norm"),
        "update_ratio": stats.get("numerics_update_ratio"),
        "max_abs_grad": stats.get("numerics_max_abs_grad"),
        "nonfinite_steps": int(
            stats.get("numerics_nonfinite_steps_total", 0)),
        "nan_skips": int(stats.get("train_nan_skips_total", 0)),
        "observe_errors": int(
            stats.get("numerics_observe_errors_total", 0)),
        "grad_anomalies": int(
            stats.get("health_anomaly_grad_norm_total", 0)),
        "grad_norm_hist": hists.get("grad_norm"),
        "per_leaf_grad_norm": {
            leaf: v for k, v in stats.items()
            if (leaf := _leaf_split(k, "numerics_grad_norm"))
            is not None},
    }
    report = {
        "anomalies": {
            "total": int(stats.get("health_anomalies_total", 0)),
            "by_signal": anomalies,
            "observe_errors": int(
                stats.get("health_observe_errors_total", 0)),
        },
        "compiles": compiles,
        "memory": memory,
        "numerics": numerics,
        "steps": {
            "train_steps_total": int(stats.get("train_steps_total", 0)),
            "train_step_ms": hists.get("train_step_ms"),
            "input_stall_pct": stats.get("input_stall_pct"),
        },
    }
    from paddle_tpu.framework.observability import flight as _flight
    prof_evs = _flight.recent(5, kind="autopilot.profile_applied")
    if prof_evs:
        # a tuned profile was consumed at startup (FLAGS_autotune_profile
        # -> maybe_apply_tuned_profile at TrainStep/PSTrainStep ctor) —
        # surface it so CI can gate on the whole chain end to end
        attrs = prof_evs[-1].get("attrs") or {}
        report["tuned_profile"] = {"path": attrs.get("path"),
                                   "source": attrs.get("source"),
                                   "knobs": attrs.get("knobs")}
    if health_snapshot is not None:
        report["detectors"] = health_snapshot.get("signals", {})
        report["compiles"]["sites"] = health_snapshot.get("compile", {})
    if trace_dir:
        import glob

        import trace_merge
        paths = sorted(glob.glob(os.path.join(trace_dir,
                                              "trace_*.jsonl")))
        if paths:
            report["spans"] = trace_merge.summarize(
                trace_merge.merge(paths))
        from paddle_tpu.framework import blame
        spans = blame.load_trace_dir(trace_dir)
        if blame_tail:
            spans = _tail_spans(spans, int(blame_tail),
                                step_span=step_span)
        res = blame.compute_blame(spans, step_span=step_span)
        if res["n_steps"]:
            # the FULL result (edges trimmed): evaluate_gates reads
            # shares/per_step_ms, and main() hands the same dict to
            # runlog.capture(blame_result=) so the ledger record does
            # not re-read and re-analyze the whole trace dir
            report["blame"] = {**res, "edges": res["edges"][:5]}
    return report


def parse_max_blame(specs) -> dict:
    """Parse repeated ``--max-blame category=pct`` specs into
    ``{category: pct}``; unknown categories and unparseable values are
    errors (a typo'd gate that silently never trips gates nothing)."""
    from paddle_tpu.framework.blame import CATEGORIES
    out = {}
    for spec in specs or ():
        if "=" not in spec:
            raise ValueError(
                f"--max-blame expects category=pct, got {spec!r}")
        cat, _, pct = spec.partition("=")
        cat = cat.strip()
        if cat not in CATEGORIES:
            raise ValueError(f"--max-blame: unknown category {cat!r} "
                             f"(one of {CATEGORIES})")
        out[cat] = float(pct)
    return out


def evaluate_gates(report: dict, max_anomalies: int = 0,
                   max_steady_recompiles: int = 0,
                   max_input_stall: Optional[float] = None,
                   max_grad_anomalies: Optional[int] = None,
                   max_blame: Optional[dict] = None,
                   expect_actions: Optional[list] = None,
                   max_actions: Optional[int] = None) -> list:
    """Returns the list of tripped-gate descriptions (empty = healthy)."""
    tripped = []
    n_anom = report["anomalies"]["total"]
    if n_anom > max_anomalies:
        tripped.append(f"anomalies: {n_anom} > {max_anomalies} "
                       f"(signals: {report['anomalies']['by_signal']})")
    n_re = report["compiles"]["jit_recompiles_steady_total"]
    if n_re > max_steady_recompiles:
        tripped.append(f"steady-state recompiles: {n_re} > "
                       f"{max_steady_recompiles} "
                       f"(causes: {report['compiles']['by_cause']})")
    stall = report["steps"].get("input_stall_pct")
    if max_input_stall is not None and stall is not None and \
            stall > max_input_stall:
        tripped.append(f"input stall: {stall:.2f}% > {max_input_stall}%")
    num = report.get("numerics") or {}
    if max_grad_anomalies is not None:
        n_g = int(num.get("grad_anomalies", 0))
        if n_g > max_grad_anomalies:
            tripped.append(f"grad-norm anomalies: {n_g} > "
                           f"{max_grad_anomalies}")
    prov = num.get("provenance")
    if prov is not None and not prov.get("ok"):
        # the seeded-NaN mini train gates itself: the nan_skip flight
        # event must name the poisoned leaf
        tripped.append(
            f"NaN provenance: expected first_bad_leaf="
            f"{prov.get('expected')!r}, got {prov.get('got')!r} "
            f"(nan_skips: {prov.get('nan_skips')})")
    if max_blame:
        bl = report.get("blame")
        if bl is None:
            tripped.append("blame gate set but no blame section "
                           "(no trace dir, or no step spans traced)")
        else:
            for cat, limit in sorted(max_blame.items()):
                pct = 100.0 * float((bl.get("shares") or {})
                                    .get(cat, 0.0))
                if pct > limit:
                    tripped.append(
                        f"blame share {cat}: {pct:.2f}% > {limit}% "
                        f"({bl.get('per_step_ms', {}).get(cat)} "
                        f"ms/step)")
    if expect_actions or max_actions is not None:
        auto = report.get("autopilot")
        if auto is None:
            tripped.append("autopilot gate set but no autopilot "
                           "section (run with --autopilot)")
        else:
            taken = [d["action"] for d in auto.get("decisions", ())
                     if d.get("kind") == "taken"]
            for name in expect_actions or ():
                if name not in taken:
                    tripped.append(
                        f"expected autopilot action {name!r} was not "
                        f"taken (taken: {taken or 'none'})")
            if max_actions is not None and len(taken) > max_actions:
                tripped.append(
                    f"autopilot actions: {len(taken)} > {max_actions} "
                    f"({taken})")
            if auto.get("act_errors"):
                tripped.append(
                    f"autopilot actuator errors: {auto['act_errors']}")
    return tripped


def format_report(report: dict, tripped: list) -> str:
    a, c, m, s = (report["anomalies"], report["compiles"],
                  report["memory"], report["steps"])
    lines = ["== health report =="]
    lines.append(f"anomalies: {a['total']}"
                 + (f"  by signal: {a['by_signal']}" if a["by_signal"]
                    else "")
                 + (f"  (observe errors: {a['observe_errors']})"
                    if a["observe_errors"] else ""))
    hit_line = (f"compiles: {c['jit_compiles_total']}  cache hits: "
                f"{c['jit_cache_hits_total']}  steady recompiles: "
                f"{c['jit_recompiles_steady_total']}")
    if c["by_cause"]:
        hit_line += f"  by cause: {c['by_cause']}"
    lines.append(hit_line)
    cms = c.get("compile_ms")
    if cms:
        lines.append(f"compile_ms: count={cms.get('count')} "
                     f"mean={cms.get('mean')} max={cms.get('max')}")
    if m["peak_bytes"]:
        mb = 1.0 / (1 << 20)
        tag_txt = "  ".join(f"{t}={b * mb:.2f}MB"
                            for t, b in sorted(m["tags"].items()))
        lines.append(f"device memory: live={m['live_bytes'] * mb:.2f}MB "
                     f"peak={m['peak_bytes'] * mb:.2f}MB"
                     + (f"  [{tag_txt}]" if tag_txt else ""))
    step_txt = f"steps: {s['train_steps_total']}"
    if s.get("train_step_ms"):
        h = s["train_step_ms"]
        step_txt += (f"  step_ms: mean={h.get('mean')} p99={h.get('p99')} "
                     f"max={h.get('max')}")
    if s.get("input_stall_pct") is not None:
        step_txt += f"  input_stall: {s['input_stall_pct']:.2f}%"
    lines.append(step_txt)
    n = report.get("numerics") or {}
    if n.get("grad_norm") is not None:
        num_txt = (f"numerics: grad_norm={n['grad_norm']:.4g} "
                   f"param_norm={n['param_norm']:.4g} "
                   f"update_ratio={n['update_ratio']:.4g} "
                   f"max_abs_grad={n['max_abs_grad']:.4g}")
        if n.get("nonfinite_steps") or n.get("nan_skips"):
            num_txt += (f"  nonfinite_steps={n['nonfinite_steps']} "
                        f"nan_skips={n['nan_skips']}")
        if n.get("grad_anomalies"):
            num_txt += f"  grad_anomalies={n['grad_anomalies']}"
        if n.get("observe_errors"):
            num_txt += f"  (observe errors: {n['observe_errors']})"
        lines.append(num_txt)
        prov = n.get("provenance")
        if prov is not None:
            lines.append(f"  provenance: expected={prov.get('expected')} "
                         f"got={prov.get('got')} "
                         f"ok={bool(prov.get('ok'))}")
        leaves = n.get("per_leaf_grad_norm") or {}
        if leaves:
            top = sorted(leaves.items(), key=lambda kv: -abs(kv[1]
                         if kv[1] == kv[1] else float("inf")))[:5]
            lines.append("  top leaf grad norms: "
                         + "  ".join(f"{k}={v:.4g}" for k, v in top))
    bl = report.get("blame")
    if bl:
        shares = bl.get("shares") or {}
        per = bl.get("per_step_ms") or {}
        parts = "  ".join(
            f"{c}={100.0 * shares.get(c, 0.0):.1f}%"
            f"({per.get(c, 0.0):.2f}ms)"
            for c in sorted(shares, key=lambda c: -shares[c])
            if shares.get(c, 0.0) > 0)
        lines.append(f"blame ({bl.get('n_steps')} steps, top="
                     f"{bl.get('top_category')}): {parts}")
        if bl.get("unresolved_links"):
            lines.append(
                f"  UNRESOLVED LINKS: {bl['unresolved_links']}")
    tp = report.get("tuned_profile")
    if tp:
        lines.append(f"tuned profile applied: source={tp.get('source')} "
                     f"knobs={tp.get('knobs')}  ({tp.get('path')})")
    auto = report.get("autopilot")
    if auto:
        snap_ = auto.get("snapshot") or {}
        lines.append(
            f"autopilot: evals={snap_.get('evals')} "
            f"decisions={snap_.get('decisions') or {}} "
            f"dry_run={snap_.get('dry_run')} "
            f"prefetch_depth={snap_.get('prefetch_depth')} "
            f"wire={snap_.get('wire_dtype')}"
            + (f"  act_errors={auto['act_errors']}"
               if auto.get("act_errors") else ""))
        for d in auto.get("decisions", ())[:12]:
            lines.append(f"  [{d.get('kind')}] step {d.get('step')}: "
                         f"{d.get('action')} — {d.get('reason')}")
    if report.get("spans"):
        import trace_merge
        lines.append("-- span summary --")
        lines.append(trace_merge.format_summary(report["spans"]))
    if tripped:
        lines.append("TRIPPED:")
        lines += [f"  - {t}" for t in tripped]
    else:
        lines.append("healthy: no gate tripped")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self-contained mini-train mode (the CI health lane)
# ---------------------------------------------------------------------------

def _make_controller(trace_dir=None, ledger_path=None, dry_run=None,
                     **targets):
    """Build the autopilot controller the ``--autopilot`` mini-train
    legs tick: targets from the leg, blame from the leg's own trace
    dir (cumulative — fine for a mini run), audit records onto the
    same ledger the run record goes to."""
    from paddle_tpu.framework import autopilot as autopilot_mod
    from paddle_tpu.framework import blame as blame_mod
    from paddle_tpu.framework import runlog
    blame_source = None
    if trace_dir is not None:
        def blame_source():
            return blame_mod.compute_blame(
                blame_mod.load_trace_dir(trace_dir))
    return autopilot_mod.Controller(
        blame_source=blame_source,
        ledger=runlog.RunLedger(ledger_path) if ledger_path else None,
        dry_run=dry_run, **targets)


def mini_train(n_steps: int, trace_dir: str, numerics: bool = False,
               nan_step: Optional[int] = None, nan_times: int = 1,
               autopilot: bool = False,
               autopilot_ledger: Optional[str] = None,
               autopilot_dry_run: Optional[bool] = None):
    """Run a traced, health-armed N-step mini train and return
    ``(monitor.snapshot(), provenance-or-None, controller-or-None)``.
    Fixed seeds and
    shapes: a healthy run compiles exactly once per jit site and trips
    zero detectors — which is precisely what the CI gate asserts.

    ``numerics=True`` arms the model-numerics plane (FLAGS_numerics +
    the grad-norm drift detectors) on a two-branch model — a dense
    head plus an independent ``aux_w * z`` branch — wrapped in
    ``ResilientTrainStep``.  ``nan_step=K`` additionally NaN-poisons
    ONLY the aux branch's input at step K (chaos ``train.step_grads``
    with ``payload_index``), so exactly one leaf's gradient goes
    non-finite: the returned provenance dict records whether the
    ``train.nan_skip`` flight event named that leaf (``aux_w``), the
    run must still finish on finite losses (skip-and-restore), and the
    grad-norm detector's baseline stays clean — the CI numerics lane's
    seeded-NaN leg.  ``nan_times=K`` widens the poison into a K-step
    storm (``every=1``) — the autopilot lane's trigger: with
    ``autopilot=True`` a controller (scaler + resilient targets; a
    ``GradScaler`` with ``decr_every=1`` is attached so the storm
    produces a ``numerics.scale_collapse``) ticks every step, and its
    decisions land on ``autopilot_ledger``."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.framework import chaos, health, monitor
    from paddle_tpu.framework import numerics as numerics_mod
    from paddle_tpu.framework.flags import get_flags, set_flags
    from paddle_tpu.framework.observability import flight, tracer
    from paddle_tpu.framework.resilient import ResilientTrainStep
    from paddle_tpu.jit import TrainStep

    for signal, kw in health.DEFAULT_SIGNALS.items():
        health.watch(signal, **dict(kw))
    saved_flags = get_flags("numerics")
    provenance = None
    ctl = None
    tracer.enable(trace_dir, label="health_check")
    try:
        paddle.seed(0)
        rng = np.random.default_rng(0)
        if not numerics:
            net = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=net.parameters())
            step = TrainStep(net,
                             lambda m, x, y: ((m(x) - y) ** 2).mean(),
                             opt)
            x = paddle.to_tensor(rng.standard_normal((16, 8))
                                 .astype(np.float32))
            y = paddle.to_tensor(rng.standard_normal((16, 4))
                                 .astype(np.float32))
            losses = [float(step(x, y)) for _ in range(n_steps)]
            assert all(np.isfinite(losses)), \
                f"mini train diverged: {losses}"
            params = net.parameters()
        else:
            set_flags({"numerics": True})
            scaler = None
            if autopilot:
                # decr_every=1: every bad step downscales, so a
                # >=4-step storm fires numerics.scale_collapse — the
                # scaler.tighten policy's trigger; streak budget must
                # outlast the storm so the CONTROLLER recovers, not a
                # train.abort
                from paddle_tpu.amp import GradScaler
                net = _two_branch_net()
                opt = paddle.optimizer.SGD(learning_rate=0.05,
                                           parameters=net.parameters())
                scaler = GradScaler(init_loss_scaling=2.0 ** 10,
                                    decr_every_n_nan_or_inf=1)
                step = ResilientTrainStep(
                    TrainStep(net, _two_branch_loss, opt), scaler=scaler,
                    max_consecutive_bad=max(10, nan_times * 2))
                ctl = _make_controller(
                    ledger_path=autopilot_ledger,
                    dry_run=autopilot_dry_run,
                    scaler=scaler, resilient=step)
            else:
                # the replay builder — incidents captured off this step
                # carry the health_check:build_incident_step descriptor
                step = build_incident_step(seed=0, lr=0.05)
                net = step.step.model
            x = paddle.to_tensor(rng.standard_normal((16, 8))
                                 .astype(np.float32))
            z = paddle.to_tensor(rng.standard_normal((4,))
                                 .astype(np.float32))
            y = paddle.to_tensor(rng.standard_normal((16, 4))
                                 .astype(np.float32))
            if nan_step is not None and nan_times == 1:
                # poison ONLY the aux branch's input (payload index 1 =
                # z): the NaN reaches exactly aux_w's gradient
                chaos.arm("train.step_grads", mode="nan",
                          nth=int(nan_step), n_times=1, payload_index=1)
            losses = []
            for i in range(n_steps):
                if nan_step is not None and nan_times > 1 and \
                        i + 1 == nan_step:
                    # storm variant, armed AT step K (nth+every don't
                    # compose into "start at K"): every step from here
                    # poisons the aux input, nan_times times
                    chaos.arm("train.step_grads", mode="nan", every=1,
                              n_times=int(nan_times), payload_index=1)
                losses.append(float(step(x, z, y)))
                if ctl is not None:
                    ctl.tick()
            assert np.isfinite(losses[-1]), \
                f"mini train did not recover: {losses[-5:]}"
            if nan_step is not None:
                skips = flight.recent(50, kind="train.nan_skip")
                got = skips[-1]["attrs"].get("first_bad_leaf") \
                    if skips else None
                # the drift detector must fire AT the poisoned step
                # too (a non-finite grad norm is an anomaly by
                # definition — Detector's z=inf rule)
                ga = int(monitor.get_stat(
                    "health_anomaly_grad_norm_total"))
                provenance = {"expected": "aux_w", "got": got,
                              "nan_skips": len(skips),
                              "grad_anomalies": ga,
                              "ok": bool(skips) and got == "aux_w"
                              and step.skipped_steps == int(nan_times)
                              and ga >= 1}
            params = net.parameters()
        health.memory.sample(tags={
            "params": sum(int(p._data.nbytes) for p in params)})
    finally:
        tracer.disable()
        if numerics:
            set_flags(saved_flags)
            chaos.disarm("train.step_grads")
            numerics_mod.reset()
    return monitor.snapshot(), provenance, ctl


def mini_train_ps(n_steps: int, trace_dir: str,
                  autopilot: bool = False,
                  autopilot_ledger: Optional[str] = None,
                  autopilot_dry_run: Optional[bool] = None):
    """PS-backed mini-train leg: the same decision surface as
    :func:`mini_train`, but the embedding rows live on an in-process
    ``PsServer`` reached over localhost TCP, so the run exercises (and
    records) real ``ps.rpc`` traffic — the observatory lane injects
    ``ps.rpc`` latency into this leg via ``FLAGS_chaos_spec``.  An
    injection armed from step 0 is a LEVEL SHIFT: the in-run detector's
    warmup adopts it (this run's gates stay green), and only the
    cross-run ledger compare (``tools/perf_report.py compare``) can see
    it — which is exactly what that lane proves.  Deterministic: fixed
    seeds, fixed shapes, sync mode, no prefetch.

    ``autopilot=True`` attaches a controller over the PS step (prefetch
    + wire actuators, blame from this leg's own trace dir) and ticks it
    every step; the loop also ANNOUNCES the next batch's ids each step,
    so a controller that deepens prefetch mid-run actually engages the
    pipeline (a no-op while depth stays 0)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                           HostEmbeddingTable,
                                           PSTrainStep)
    from paddle_tpu.distributed.ps.service import (PsClient, PsServer,
                                                   RemoteEmbeddingTable)
    from paddle_tpu.framework import health, monitor
    from paddle_tpu.framework.observability import tracer

    from paddle_tpu.models import WideDeepHost

    for signal, kw in health.DEFAULT_SIGNALS.items():
        health.watch(signal, **dict(kw))
    tracer.enable(trace_dir, label="health_check_ps")
    table = HostEmbeddingTable(256, 9, optimizer="sgd",
                               learning_rate=0.05, seed=0)
    srv = PsServer({"emb": table}, port=0).start()
    cli = PsClient([f"127.0.0.1:{srv.port}"], wire_dtype="f32",
                   backoff_base=0.01)
    try:
        paddle.seed(0)
        emb = DistributedEmbedding(
            256, 9, mode="sync",
            table=RemoteEmbeddingTable(cli, "emb", 9))
        # autopilot leg: a model heavy enough (~5ms compute/step on
        # CPU) that a RECOVERED step is compute-dominated — the
        # --blame-tail gate can then tell "storm hidden" from "storm
        # still raging" by the ps_wait share alone
        hidden = (256, 256, 256) if autopilot else (16,)
        bs = 256 if autopilot else 8
        model = WideDeepHost(embedding_dim=8, num_fields=4, dense_dim=3,
                             hidden=hidden)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())

        def loss_fn(m, rows, x, y):
            return F.binary_cross_entropy_with_logits(
                m(rows, x), y).mean()

        step = PSTrainStep(model, loss_fn, opt, emb,
                           transfer_dtype="float32", prefetch_depth=0)
        ctl = None
        if autopilot:
            ctl = _make_controller(trace_dir=trace_dir,
                                   ledger_path=autopilot_ledger,
                                   dry_run=autopilot_dry_run,
                                   step=step, client=cli)
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 256,
                           size=(n_steps, bs, 4)).astype(np.int64)
        x = paddle.to_tensor(rng.standard_normal((bs, 3))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.random((bs, 1)).astype(np.float32))
        losses = []
        for n in range(n_steps):
            if ctl is not None and n + 1 < n_steps:
                step.prefetch(ids[n + 1])
            losses.append(float(step(ids[n], x, y)))
            if ctl is not None:
                ctl.tick()
        assert all(np.isfinite(losses)), \
            f"PS mini train diverged: {losses[-5:]}"
        step.flush()
    finally:
        try:
            cli.bye()
        finally:
            srv.shutdown()
            tracer.disable()
    return monitor.snapshot(), None, ctl


def mini_train_zero(n_steps: int, trace_dir: str, wire: str = "f32",
                    ring: bool = False):
    """ZeRO-sharded mini-train leg: the same decision surface as
    :func:`mini_train`, but the step is the fused
    ``ShardedUpdateTrainStep`` on a dp=2 mesh of CPU virtual devices,
    so the run exercises (and records) the fused reduce-scatter /
    all-gather pair.  Per-step wire bytes land on the
    ``zero_collective_bytes_per_step`` stat (whitelisted into the
    ledger summary — the observatory's wire-byte series), and under
    the armed tracer the ``zero.reduce_scatter`` / ``zero.all_gather``
    leg spans fence the dispatch, so the fused collectives' wall time
    claims blame as ``collective``.  ``wire``/``ring`` select the
    collective codec and the chunked ring schedule (passed to the step
    directly — no flag mutation).  Deterministic: fixed seeds, fixed
    shapes."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer
    from paddle_tpu.framework import health, monitor
    from paddle_tpu.framework.observability import tracer
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.zero import ShardedUpdateTrainStep

    if len(jax.devices()) < 2:
        raise RuntimeError(
            "--zero needs >= 2 devices for a dp=2 mesh (jax "
            "initialized before the CPU virtual-device flag could be "
            "set; export XLA_FLAGS="
            "--xla_force_host_platform_device_count=8)")
    for signal, kw in health.DEFAULT_SIGNALS.items():
        health.watch(signal, **dict(kw))
    tracer.enable(trace_dir, label="health_check_zero")
    try:
        paddle.seed(0)
        rng = np.random.default_rng(0)
        mesh = make_mesh({"dp": 2}, devices=jax.devices()[:2])
        model = nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                              nn.Linear(64, 32))
        opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                 parameters=model.parameters())
        step = ShardedUpdateTrainStep(
            model, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt,
            mesh=mesh, wire_dtype=wire, ring=ring)
        x = paddle.to_tensor(rng.standard_normal((8, 32))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((8, 32))
                             .astype(np.float32))
        losses = [float(step(x, y)) for _ in range(n_steps)]
        assert all(np.isfinite(losses)), \
            f"ZeRO mini train diverged: {losses[-5:]}"
        health.memory.sample(tags={
            "params": sum(int(p._data.nbytes)
                          for p in model.parameters())})
    finally:
        tracer.disable()
    return monitor.snapshot(), None, None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="health_check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--metrics", default=None,
                    help="metrics snapshot: monitor.snapshot() JSON or "
                         "Prometheus text (MetricsReporter output)")
    ap.add_argument("--trace-dir", default=None,
                    help="directory of trace_*.jsonl span files "
                         "(adds the per-span summary to the report)")
    ap.add_argument("--mini-train", type=int, default=None, metavar="N",
                    help="self-contained mode: run a traced, "
                         "health-armed N-step mini train and evaluate "
                         "its own snapshot (the CI health lane)")
    ap.add_argument("--numerics", action="store_true",
                    help="mini-train option: arm the model-numerics "
                         "plane (FLAGS_numerics + grad-norm drift "
                         "detectors) on a two-branch model under "
                         "ResilientTrainStep")
    ap.add_argument("--nan-step", type=int, default=None, metavar="K",
                    help="mini-train option (implies --numerics): NaN-"
                         "poison only the aux branch's input at step K "
                         "and gate that train.nan_skip names that "
                         "branch's leaf as first_bad_leaf (the CI "
                         "numerics lane's seeded-NaN leg)")
    ap.add_argument("--ps", action="store_true",
                    help="mini-train option: run the PS-backed leg "
                         "(in-process PsServer over localhost TCP) so "
                         "real ps.rpc traffic feeds the detectors and "
                         "the run record")
    ap.add_argument("--zero", action="store_true",
                    help="mini-train option: run the ZeRO-sharded leg "
                         "(fused reduce-scatter/all-gather on a dp=2 "
                         "mesh of CPU virtual devices) so collective "
                         "wire bytes and collective blame feed the "
                         "detectors and the run record")
    ap.add_argument("--zero-wire", default="f32",
                    choices=("f32", "bf16", "int8", "int4"),
                    help="--zero option: collective wire codec "
                         "(default f32)")
    ap.add_argument("--zero-ring", action="store_true",
                    help="--zero option: use the fused chunked-ring "
                         "collectives (parallel/ring.py) instead of "
                         "the native psum_scatter/all_gather pair")
    ap.add_argument("--nan-storm", type=int, default=None, metavar="T",
                    help="mini-train option (with --nan-step K): widen "
                         "the poison into a T-step storm starting at "
                         "K — the autopilot lane's numerics trigger")
    ap.add_argument("--autopilot", action="store_true",
                    help="mini-train option: attach the runtime "
                         "controller (framework/autopilot.py) to the "
                         "leg's targets and tick it every step; its "
                         "snapshot + decision audit join the report")
    ap.add_argument("--autopilot-dry-run", action="store_true",
                    help="autopilot option: compute and record "
                         "decisions but mutate nothing")
    ap.add_argument("--expect-action", action="append", default=None,
                    metavar="NAME",
                    help="gate (repeatable): the autopilot must have "
                         "TAKEN an action with this name, e.g. "
                         "--expect-action prefetch.deepen")
    ap.add_argument("--max-actions", type=int, default=None,
                    help="gate: tolerated autopilot actions taken "
                         "(0 = a clean run must leave the knobs "
                         "alone)")
    ap.add_argument("--blame-tail", type=int, default=None, metavar="N",
                    help="compute blame over only the last N steps' "
                         "spans — gates recovery (did the top category "
                         "return to compute AFTER the controller "
                         "acted) instead of the cumulative average")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="append a RunRecord (runlog.capture) for this "
                         "mini train to the run ledger at PATH — the "
                         "perf observatory's producer hook")
    ap.add_argument("--run-label", default=None,
                    help="RunRecord label (default: 'ps' or 'dense' "
                         "per the mini-train variant)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--max-anomalies", type=int, default=0,
                    help="gate: tolerated health_anomalies_total "
                         "(default 0)")
    ap.add_argument("--max-steady-recompiles", type=int, default=0,
                    help="gate: tolerated post-warmup recompiles "
                         "(default 0)")
    ap.add_argument("--max-input-stall", type=float, default=None,
                    help="gate: tolerated input_stall_pct (off by "
                         "default)")
    ap.add_argument("--max-grad-anomalies", type=int, default=None,
                    help="gate: tolerated grad-norm detector anomalies "
                         "(health_anomaly_grad_norm_total; off by "
                         "default)")
    ap.add_argument("--max-blame", action="append", default=None,
                    metavar="CATEGORY=PCT",
                    help="gate (repeatable): tolerated blame share per "
                         "category from the causal critical-path "
                         "analysis, e.g. --max-blame ps_wait=30 — "
                         "requires a trace (mini-train or "
                         "--trace-dir); categories: compute, ps_wait, "
                         "ingest_wait, collective, compile, other")
    a = ap.parse_args(argv)
    try:
        max_blame = parse_max_blame(a.max_blame)
    except ValueError as e:
        ap.error(str(e))
    if a.metrics is None and a.mini_train is None:
        ap.error("nothing to check: pass --metrics or --mini-train")
    if a.metrics is not None and a.mini_train is not None:
        ap.error("--metrics and --mini-train are mutually exclusive: "
                 "the mini train evaluates its own fresh snapshot")
    if a.nan_step is not None:
        a.numerics = True
    if a.numerics and a.mini_train is None:
        ap.error("--numerics/--nan-step are mini-train options")
    if a.ps and a.mini_train is None:
        ap.error("--ps is a mini-train option")
    if a.ps and a.numerics:
        ap.error("--ps and --numerics/--nan-step are separate "
                 "mini-train legs — run them as two invocations")
    if a.zero and a.mini_train is None:
        ap.error("--zero is a mini-train option")
    if a.zero and (a.ps or a.numerics):
        ap.error("--zero, --ps and --numerics/--nan-step are separate "
                 "mini-train legs — run them as separate invocations")
    if (a.zero_ring or a.zero_wire != "f32") and not a.zero:
        ap.error("--zero-wire/--zero-ring are --zero options")
    if a.autopilot and a.zero:
        ap.error("--autopilot has no actuators on the --zero leg")
    if a.ledger is not None and a.mini_train is None:
        ap.error("--ledger records a mini train; pass --mini-train")
    if a.autopilot and a.mini_train is None:
        ap.error("--autopilot is a mini-train option")
    if a.autopilot and not (a.ps or a.numerics):
        ap.error("--autopilot needs targets: run the --ps leg "
                 "(prefetch/wire actuators) or the --numerics leg "
                 "(scaler/resilient actuators)")
    if a.nan_storm is not None and a.nan_step is None:
        ap.error("--nan-storm widens --nan-step; pass both")

    health_snapshot = None
    provenance = None
    ctl = None
    if a.mini_train is not None:
        if a.trace_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="health_check_")
            a.trace_dir = tmp.name          # kept alive by the local ref
        if a.ps:
            snap, provenance, ctl = mini_train_ps(
                a.mini_train, a.trace_dir, autopilot=a.autopilot,
                autopilot_ledger=a.ledger,
                autopilot_dry_run=a.autopilot_dry_run or None)
        elif a.zero:
            snap, provenance, ctl = mini_train_zero(
                a.mini_train, a.trace_dir, wire=a.zero_wire,
                ring=a.zero_ring)
        else:
            snap, provenance, ctl = mini_train(
                a.mini_train, a.trace_dir, numerics=a.numerics,
                nan_step=a.nan_step, nan_times=a.nan_storm or 1,
                autopilot=a.autopilot, autopilot_ledger=a.ledger,
                autopilot_dry_run=a.autopilot_dry_run or None)
        from paddle_tpu.framework import health
        health_snapshot = health.snapshot()
    else:
        snap = load_metrics(a.metrics)

    report = build_report(snap, trace_dir=a.trace_dir,
                          health_snapshot=health_snapshot,
                          blame_tail=a.blame_tail,
                          step_span="zero.step" if a.zero
                          else "train.step")
    if provenance is not None:
        report["numerics"]["provenance"] = provenance
    if ctl is not None:
        from paddle_tpu.framework import monitor as monitor_mod
        report["autopilot"] = {
            "snapshot": ctl.snapshot(),
            "decisions": list(ctl.decisions),
            "act_errors": int(monitor_mod.get_stat(
                "autopilot_act_errors_total") or 0)}
    tripped = evaluate_gates(
        report, max_anomalies=a.max_anomalies,
        max_steady_recompiles=a.max_steady_recompiles,
        max_input_stall=a.max_input_stall,
        max_grad_anomalies=a.max_grad_anomalies,
        max_blame=max_blame,
        expect_actions=a.expect_action,
        max_actions=a.max_actions)
    report["tripped"] = tripped
    if a.ledger is not None:
        # one RunRecord per mini train, appended AFTER the gates ran so
        # the verdict rides along; RunLedger.append never raises
        from paddle_tpu.framework import runlog
        label = a.run_label or ("ps" if a.ps else
                                "zero" if a.zero else
                                "numerics" if a.numerics else "dense")
        rec = runlog.capture("health_check", label=label,
                             trace_dir=a.trace_dir,
                             blame_result=report.get("blame"),
                             extra={"steps": a.mini_train,
                                    "tripped": tripped})
        runlog.RunLedger(a.ledger).append(rec)
    if a.format == "json":
        print(json.dumps(report, indent=1, default=str))
    else:
        print(format_report(report, tripped))
    return 1 if tripped else 0


if __name__ == "__main__":
    sys.exit(main())
