#!/usr/bin/env python
"""Health report CLI — the perf health plane's decision surface.

Renders one report (text or JSON) from a metrics snapshot and/or a
trace directory, and exits nonzero when a gate trips — so CI, a
launcher wrapper, and the future autotuner all consume the same
verdict the detectors produce:

* **anomalies** — ``health_anomalies_total`` (+ the per-signal
  ``health_anomaly_<signal>_total`` split) from the streaming
  detectors (framework/health.py);
* **compiles** — ``jit_compiles_total`` / ``jit_cache_hits_total`` /
  per-cause counters, the ``compile_ms`` histogram, and the
  steady-state recompile count the compile-storm detector feeds;
* **memory** — ``device_mem_live_bytes`` / ``device_mem_peak_bytes``
  and the per-tag attribution gauges;
* **spans** — the per-span-name aggregate table
  (``tools/trace_merge.py summarize``) over ``--trace-dir``.

Inputs:

* ``--metrics FILE`` — a ``monitor.snapshot()`` JSON file, or a
  Prometheus text rendering (``MetricsReporter`` output; gauges and
  ``_total`` counters are read, histogram summaries need the JSON
  form);
* ``--trace-dir DIR`` — per-process ``trace_*.jsonl`` span files;
* ``--mini-train N`` — self-contained mode: run a traced N-step mini
  train with the default detectors armed, snapshot, and evaluate
  in-process (the CI health lane; no files needed).

Gates (any trip → exit 1): ``--max-anomalies`` (default 0),
``--max-steady-recompiles`` (default 0), ``--max-input-stall``
(percent; off by default).

Usage::

    python tools/health_check.py --mini-train 30
    python tools/health_check.py --metrics snap.json --trace-dir /tmp/tr
    python tools/health_check.py --metrics metrics.prom --format json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

__all__ = ["load_metrics", "build_report", "evaluate_gates",
           "format_report", "mini_train", "main"]


# ---------------------------------------------------------------------------
# input loading
# ---------------------------------------------------------------------------

def _parse_prometheus(text: str) -> dict:
    """Reduce a Prometheus text rendering to the snapshot shape: plain
    samples become stats; histogram ``_sum``/``_count`` pairs become
    minimal histogram records (no percentiles — the JSON snapshot form
    carries those)."""
    stats = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" in line:
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        try:
            stats[parts[0]] = float(parts[1])
        except ValueError:
            continue
    hists = {}
    for name, v in list(stats.items()):
        if name.endswith("_count") and name[:-len("_count")] + "_sum" \
                in stats:
            base = name[:-len("_count")]
            count = int(v)
            total = stats[base + "_sum"]
            hists[base] = {"count": count, "sum": total,
                           "mean": total / count if count else 0.0}
    return {"stats": stats, "histograms": hists}


def load_metrics(path: str) -> dict:
    """Load a metrics snapshot: ``monitor.snapshot()`` JSON or a
    Prometheus text file (sniffed by the leading character)."""
    with open(path) as f:
        text = f.read()
    if text.lstrip().startswith("{"):
        snap = json.loads(text)
        snap.setdefault("stats", {})
        snap.setdefault("histograms", {})
        return snap
    return _parse_prometheus(text)


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def build_report(snap: dict, trace_dir: Optional[str] = None,
                 health_snapshot: Optional[dict] = None) -> dict:
    """Fold a metrics snapshot (+ optional trace dir and live health
    state) into the report dict the gates and renderers consume."""
    stats = snap.get("stats", {})
    hists = snap.get("histograms", {})

    anomalies = {k: int(v) for k, v in stats.items()
                 if k.startswith("health_anomaly_") and k.endswith("_total")}
    compiles = {
        "jit_compiles_total": int(stats.get("jit_compiles_total", 0)),
        "jit_cache_hits_total": int(stats.get("jit_cache_hits_total", 0)),
        "jit_recompiles_steady_total": int(
            stats.get("jit_recompiles_steady_total", 0)),
        "by_cause": {k[len("jit_compiles_"):-len("_total")]: int(v)
                     for k, v in stats.items()
                     if k.startswith("jit_compiles_") and
                     k.endswith("_total") and k != "jit_compiles_total"},
        "compile_ms": hists.get("compile_ms"),
    }
    memory = {
        "live_bytes": int(stats.get("device_mem_live_bytes", 0)),
        "peak_bytes": int(stats.get("device_mem_peak_bytes", 0)),
        "tags": {k[len("device_mem_"):-len("_bytes")]: int(v)
                 for k, v in stats.items()
                 if k.startswith("device_mem_") and k.endswith("_bytes")
                 and k not in ("device_mem_live_bytes",
                               "device_mem_peak_bytes")},
    }
    report = {
        "anomalies": {
            "total": int(stats.get("health_anomalies_total", 0)),
            "by_signal": anomalies,
            "observe_errors": int(
                stats.get("health_observe_errors_total", 0)),
        },
        "compiles": compiles,
        "memory": memory,
        "steps": {
            "train_steps_total": int(stats.get("train_steps_total", 0)),
            "train_step_ms": hists.get("train_step_ms"),
            "input_stall_pct": stats.get("input_stall_pct"),
        },
    }
    if health_snapshot is not None:
        report["detectors"] = health_snapshot.get("signals", {})
        report["compiles"]["sites"] = health_snapshot.get("compile", {})
    if trace_dir:
        import glob

        import trace_merge
        paths = sorted(glob.glob(os.path.join(trace_dir,
                                              "trace_*.jsonl")))
        if paths:
            report["spans"] = trace_merge.summarize(
                trace_merge.merge(paths))
    return report


def evaluate_gates(report: dict, max_anomalies: int = 0,
                   max_steady_recompiles: int = 0,
                   max_input_stall: Optional[float] = None) -> list:
    """Returns the list of tripped-gate descriptions (empty = healthy)."""
    tripped = []
    n_anom = report["anomalies"]["total"]
    if n_anom > max_anomalies:
        tripped.append(f"anomalies: {n_anom} > {max_anomalies} "
                       f"(signals: {report['anomalies']['by_signal']})")
    n_re = report["compiles"]["jit_recompiles_steady_total"]
    if n_re > max_steady_recompiles:
        tripped.append(f"steady-state recompiles: {n_re} > "
                       f"{max_steady_recompiles} "
                       f"(causes: {report['compiles']['by_cause']})")
    stall = report["steps"].get("input_stall_pct")
    if max_input_stall is not None and stall is not None and \
            stall > max_input_stall:
        tripped.append(f"input stall: {stall:.2f}% > {max_input_stall}%")
    return tripped


def format_report(report: dict, tripped: list) -> str:
    a, c, m, s = (report["anomalies"], report["compiles"],
                  report["memory"], report["steps"])
    lines = ["== health report =="]
    lines.append(f"anomalies: {a['total']}"
                 + (f"  by signal: {a['by_signal']}" if a["by_signal"]
                    else "")
                 + (f"  (observe errors: {a['observe_errors']})"
                    if a["observe_errors"] else ""))
    hit_line = (f"compiles: {c['jit_compiles_total']}  cache hits: "
                f"{c['jit_cache_hits_total']}  steady recompiles: "
                f"{c['jit_recompiles_steady_total']}")
    if c["by_cause"]:
        hit_line += f"  by cause: {c['by_cause']}"
    lines.append(hit_line)
    cms = c.get("compile_ms")
    if cms:
        lines.append(f"compile_ms: count={cms.get('count')} "
                     f"mean={cms.get('mean')} max={cms.get('max')}")
    if m["peak_bytes"]:
        mb = 1.0 / (1 << 20)
        tag_txt = "  ".join(f"{t}={b * mb:.2f}MB"
                            for t, b in sorted(m["tags"].items()))
        lines.append(f"device memory: live={m['live_bytes'] * mb:.2f}MB "
                     f"peak={m['peak_bytes'] * mb:.2f}MB"
                     + (f"  [{tag_txt}]" if tag_txt else ""))
    step_txt = f"steps: {s['train_steps_total']}"
    if s.get("train_step_ms"):
        h = s["train_step_ms"]
        step_txt += (f"  step_ms: mean={h.get('mean')} p99={h.get('p99')} "
                     f"max={h.get('max')}")
    if s.get("input_stall_pct") is not None:
        step_txt += f"  input_stall: {s['input_stall_pct']:.2f}%"
    lines.append(step_txt)
    if report.get("spans"):
        import trace_merge
        lines.append("-- span summary --")
        lines.append(trace_merge.format_summary(report["spans"]))
    if tripped:
        lines.append("TRIPPED:")
        lines += [f"  - {t}" for t in tripped]
    else:
        lines.append("healthy: no gate tripped")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self-contained mini-train mode (the CI health lane)
# ---------------------------------------------------------------------------

def mini_train(n_steps: int, trace_dir: str) -> dict:
    """Run a traced, health-armed N-step mini train and return
    ``monitor.snapshot()``.  Fixed seeds and shapes: a healthy run
    compiles exactly once per jit site and trips zero detectors —
    which is precisely what the CI gate asserts."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.framework import health, monitor
    from paddle_tpu.framework.observability import tracer
    from paddle_tpu.jit import TrainStep

    for signal, kw in health.DEFAULT_SIGNALS.items():
        health.watch(signal, **dict(kw))
    tracer.enable(trace_dir, label="health_check")
    try:
        paddle.seed(0)
        net = nn.Linear(8, 4)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        step = TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(),
                         opt)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((16, 8))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.standard_normal((16, 4))
                             .astype(np.float32))
        losses = [float(step(x, y)) for _ in range(n_steps)]
        assert all(np.isfinite(losses)), f"mini train diverged: {losses}"
        health.memory.sample(tags={
            "params": sum(int(p._data.nbytes) for p in net.parameters())})
    finally:
        tracer.disable()
    return monitor.snapshot()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="health_check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--metrics", default=None,
                    help="metrics snapshot: monitor.snapshot() JSON or "
                         "Prometheus text (MetricsReporter output)")
    ap.add_argument("--trace-dir", default=None,
                    help="directory of trace_*.jsonl span files "
                         "(adds the per-span summary to the report)")
    ap.add_argument("--mini-train", type=int, default=None, metavar="N",
                    help="self-contained mode: run a traced, "
                         "health-armed N-step mini train and evaluate "
                         "its own snapshot (the CI health lane)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--max-anomalies", type=int, default=0,
                    help="gate: tolerated health_anomalies_total "
                         "(default 0)")
    ap.add_argument("--max-steady-recompiles", type=int, default=0,
                    help="gate: tolerated post-warmup recompiles "
                         "(default 0)")
    ap.add_argument("--max-input-stall", type=float, default=None,
                    help="gate: tolerated input_stall_pct (off by "
                         "default)")
    a = ap.parse_args(argv)
    if a.metrics is None and a.mini_train is None:
        ap.error("nothing to check: pass --metrics or --mini-train")
    if a.metrics is not None and a.mini_train is not None:
        ap.error("--metrics and --mini-train are mutually exclusive: "
                 "the mini train evaluates its own fresh snapshot")

    health_snapshot = None
    if a.mini_train is not None:
        if a.trace_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="health_check_")
            a.trace_dir = tmp.name          # kept alive by the local ref
        snap = mini_train(a.mini_train, a.trace_dir)
        from paddle_tpu.framework import health
        health_snapshot = health.snapshot()
    else:
        snap = load_metrics(a.metrics)

    report = build_report(snap, trace_dir=a.trace_dir,
                          health_snapshot=health_snapshot)
    tripped = evaluate_gates(
        report, max_anomalies=a.max_anomalies,
        max_steady_recompiles=a.max_steady_recompiles,
        max_input_stall=a.max_input_stall)
    report["tripped"] = tripped
    if a.format == "json":
        print(json.dumps(report, indent=1, default=str))
    else:
        print(format_report(report, tripped))
    return 1 if tripped else 0


if __name__ == "__main__":
    sys.exit(main())
