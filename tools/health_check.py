#!/usr/bin/env python
"""Health report CLI — the perf health plane's decision surface.

Renders one report (text or JSON) from a metrics snapshot and/or a
trace directory, and exits nonzero when a gate trips — so CI, a
launcher wrapper, and the future autotuner all consume the same
verdict the detectors produce:

* **anomalies** — ``health_anomalies_total`` (+ the per-signal
  ``health_anomaly_<signal>_total`` split) from the streaming
  detectors (framework/health.py);
* **compiles** — ``jit_compiles_total`` / ``jit_cache_hits_total`` /
  per-cause counters, the ``compile_ms`` histogram, and the
  steady-state recompile count the compile-storm detector feeds;
* **memory** — ``device_mem_live_bytes`` / ``device_mem_peak_bytes``
  and the per-tag attribution gauges;
* **numerics** — the model-numerics plane (framework/numerics.py):
  global grad/param norms, update ratio, max-abs grad, non-finite
  step + NaN-skip counts, grad-norm detector anomalies, the sampled
  per-leaf grad norms, and (mini-train ``--nan-step``) the NaN
  provenance verdict;
* **spans** — the per-span-name aggregate table
  (``tools/trace_merge.py summarize``) over ``--trace-dir``.

Inputs:

* ``--metrics FILE`` — a ``monitor.snapshot()`` JSON file, or a
  Prometheus text rendering (``MetricsReporter`` output; gauges and
  ``_total`` counters are read, histogram summaries need the JSON
  form);
* ``--trace-dir DIR`` — per-process ``trace_*.jsonl`` span files;
* ``--mini-train N`` — self-contained mode: run a traced N-step mini
  train with the default detectors armed, snapshot, and evaluate
  in-process (the CI health lane; no files needed).

Gates (any trip → exit 1): ``--max-anomalies`` (default 0),
``--max-steady-recompiles`` (default 0), ``--max-input-stall``
(percent; off by default), ``--max-grad-anomalies`` (grad-norm
detector trips; off by default), ``--max-blame category=pct``
(repeatable; blame-share ceiling per causal category from
``framework/blame.py`` — requires a trace), and — implicit with
``--nan-step`` — the NaN-provenance verdict (the seeded fault must be
attributed to the poisoned leaf).

Usage::

    python tools/health_check.py --mini-train 30
    python tools/health_check.py --mini-train 30 --numerics \\
        --max-grad-anomalies 0
    python tools/health_check.py --mini-train 30 --nan-step 20
    python tools/health_check.py --metrics snap.json --trace-dir /tmp/tr
    python tools/health_check.py --metrics metrics.prom --format json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

__all__ = ["load_metrics", "build_report", "evaluate_gates",
           "parse_max_blame", "format_report", "mini_train",
           "mini_train_ps", "main"]


# ---------------------------------------------------------------------------
# input loading
# ---------------------------------------------------------------------------

def _parse_prometheus(text: str) -> dict:
    """Reduce a Prometheus text rendering to the snapshot shape: plain
    samples become stats; histogram ``_sum``/``_count`` pairs become
    minimal histogram records (no percentiles — the JSON snapshot form
    carries those)."""
    stats = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" in line:
            continue
        parts = line.split()
        if len(parts) < 2:
            continue
        try:
            stats[parts[0]] = float(parts[1])
        except ValueError:
            continue
    hists = {}
    for name, v in list(stats.items()):
        if name.endswith("_count") and name[:-len("_count")] + "_sum" \
                in stats:
            base = name[:-len("_count")]
            count = int(v)
            total = stats[base + "_sum"]
            hists[base] = {"count": count, "sum": total,
                           "mean": total / count if count else 0.0}
    return {"stats": stats, "histograms": hists}


def load_metrics(path: str) -> dict:
    """Load a metrics snapshot: ``monitor.snapshot()`` JSON or a
    Prometheus text file (sniffed by the leading character)."""
    with open(path) as f:
        text = f.read()
    if text.lstrip().startswith("{"):
        snap = json.loads(text)
        snap.setdefault("stats", {})
        snap.setdefault("histograms", {})
        return snap
    return _parse_prometheus(text)


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------

def build_report(snap: dict, trace_dir: Optional[str] = None,
                 health_snapshot: Optional[dict] = None) -> dict:
    """Fold a metrics snapshot (+ optional trace dir and live health
    state) into the report dict the gates and renderers consume."""
    stats = snap.get("stats", {})
    hists = snap.get("histograms", {})

    anomalies = {k: int(v) for k, v in stats.items()
                 if k.startswith("health_anomaly_") and k.endswith("_total")}
    compiles = {
        "jit_compiles_total": int(stats.get("jit_compiles_total", 0)),
        "jit_cache_hits_total": int(stats.get("jit_cache_hits_total", 0)),
        "jit_recompiles_steady_total": int(
            stats.get("jit_recompiles_steady_total", 0)),
        "by_cause": {k[len("jit_compiles_"):-len("_total")]: int(v)
                     for k, v in stats.items()
                     if k.startswith("jit_compiles_") and
                     k.endswith("_total") and k != "jit_compiles_total"},
        "compile_ms": hists.get("compile_ms"),
    }
    memory = {
        "live_bytes": int(stats.get("device_mem_live_bytes", 0)),
        "peak_bytes": int(stats.get("device_mem_peak_bytes", 0)),
        "tags": {k[len("device_mem_"):-len("_bytes")]: int(v)
                 for k, v in stats.items()
                 if k.startswith("device_mem_") and k.endswith("_bytes")
                 and k not in ("device_mem_live_bytes",
                               "device_mem_peak_bytes")},
    }
    def _leaf_split(k, prefix):
        # per-leaf numerics gauges: "numerics_grad_norm[fc.weight]"
        return k[len(prefix) + 1:-1] if k.startswith(prefix + "[") \
            and k.endswith("]") else None

    numerics = {
        "grad_norm": stats.get("numerics_grad_norm"),
        "param_norm": stats.get("numerics_param_norm"),
        "update_ratio": stats.get("numerics_update_ratio"),
        "max_abs_grad": stats.get("numerics_max_abs_grad"),
        "nonfinite_steps": int(
            stats.get("numerics_nonfinite_steps_total", 0)),
        "nan_skips": int(stats.get("train_nan_skips_total", 0)),
        "observe_errors": int(
            stats.get("numerics_observe_errors_total", 0)),
        "grad_anomalies": int(
            stats.get("health_anomaly_grad_norm_total", 0)),
        "grad_norm_hist": hists.get("grad_norm"),
        "per_leaf_grad_norm": {
            leaf: v for k, v in stats.items()
            if (leaf := _leaf_split(k, "numerics_grad_norm"))
            is not None},
    }
    report = {
        "anomalies": {
            "total": int(stats.get("health_anomalies_total", 0)),
            "by_signal": anomalies,
            "observe_errors": int(
                stats.get("health_observe_errors_total", 0)),
        },
        "compiles": compiles,
        "memory": memory,
        "numerics": numerics,
        "steps": {
            "train_steps_total": int(stats.get("train_steps_total", 0)),
            "train_step_ms": hists.get("train_step_ms"),
            "input_stall_pct": stats.get("input_stall_pct"),
        },
    }
    if health_snapshot is not None:
        report["detectors"] = health_snapshot.get("signals", {})
        report["compiles"]["sites"] = health_snapshot.get("compile", {})
    if trace_dir:
        import glob

        import trace_merge
        paths = sorted(glob.glob(os.path.join(trace_dir,
                                              "trace_*.jsonl")))
        if paths:
            report["spans"] = trace_merge.summarize(
                trace_merge.merge(paths))
        from paddle_tpu.framework import blame
        res = blame.compute_blame(blame.load_trace_dir(trace_dir))
        if res["n_steps"]:
            # the FULL result (edges trimmed): evaluate_gates reads
            # shares/per_step_ms, and main() hands the same dict to
            # runlog.capture(blame_result=) so the ledger record does
            # not re-read and re-analyze the whole trace dir
            report["blame"] = {**res, "edges": res["edges"][:5]}
    return report


def parse_max_blame(specs) -> dict:
    """Parse repeated ``--max-blame category=pct`` specs into
    ``{category: pct}``; unknown categories and unparseable values are
    errors (a typo'd gate that silently never trips gates nothing)."""
    from paddle_tpu.framework.blame import CATEGORIES
    out = {}
    for spec in specs or ():
        if "=" not in spec:
            raise ValueError(
                f"--max-blame expects category=pct, got {spec!r}")
        cat, _, pct = spec.partition("=")
        cat = cat.strip()
        if cat not in CATEGORIES:
            raise ValueError(f"--max-blame: unknown category {cat!r} "
                             f"(one of {CATEGORIES})")
        out[cat] = float(pct)
    return out


def evaluate_gates(report: dict, max_anomalies: int = 0,
                   max_steady_recompiles: int = 0,
                   max_input_stall: Optional[float] = None,
                   max_grad_anomalies: Optional[int] = None,
                   max_blame: Optional[dict] = None) -> list:
    """Returns the list of tripped-gate descriptions (empty = healthy)."""
    tripped = []
    n_anom = report["anomalies"]["total"]
    if n_anom > max_anomalies:
        tripped.append(f"anomalies: {n_anom} > {max_anomalies} "
                       f"(signals: {report['anomalies']['by_signal']})")
    n_re = report["compiles"]["jit_recompiles_steady_total"]
    if n_re > max_steady_recompiles:
        tripped.append(f"steady-state recompiles: {n_re} > "
                       f"{max_steady_recompiles} "
                       f"(causes: {report['compiles']['by_cause']})")
    stall = report["steps"].get("input_stall_pct")
    if max_input_stall is not None and stall is not None and \
            stall > max_input_stall:
        tripped.append(f"input stall: {stall:.2f}% > {max_input_stall}%")
    num = report.get("numerics") or {}
    if max_grad_anomalies is not None:
        n_g = int(num.get("grad_anomalies", 0))
        if n_g > max_grad_anomalies:
            tripped.append(f"grad-norm anomalies: {n_g} > "
                           f"{max_grad_anomalies}")
    prov = num.get("provenance")
    if prov is not None and not prov.get("ok"):
        # the seeded-NaN mini train gates itself: the nan_skip flight
        # event must name the poisoned leaf
        tripped.append(
            f"NaN provenance: expected first_bad_leaf="
            f"{prov.get('expected')!r}, got {prov.get('got')!r} "
            f"(nan_skips: {prov.get('nan_skips')})")
    if max_blame:
        bl = report.get("blame")
        if bl is None:
            tripped.append("blame gate set but no blame section "
                           "(no trace dir, or no step spans traced)")
        else:
            for cat, limit in sorted(max_blame.items()):
                pct = 100.0 * float((bl.get("shares") or {})
                                    .get(cat, 0.0))
                if pct > limit:
                    tripped.append(
                        f"blame share {cat}: {pct:.2f}% > {limit}% "
                        f"({bl.get('per_step_ms', {}).get(cat)} "
                        f"ms/step)")
    return tripped


def format_report(report: dict, tripped: list) -> str:
    a, c, m, s = (report["anomalies"], report["compiles"],
                  report["memory"], report["steps"])
    lines = ["== health report =="]
    lines.append(f"anomalies: {a['total']}"
                 + (f"  by signal: {a['by_signal']}" if a["by_signal"]
                    else "")
                 + (f"  (observe errors: {a['observe_errors']})"
                    if a["observe_errors"] else ""))
    hit_line = (f"compiles: {c['jit_compiles_total']}  cache hits: "
                f"{c['jit_cache_hits_total']}  steady recompiles: "
                f"{c['jit_recompiles_steady_total']}")
    if c["by_cause"]:
        hit_line += f"  by cause: {c['by_cause']}"
    lines.append(hit_line)
    cms = c.get("compile_ms")
    if cms:
        lines.append(f"compile_ms: count={cms.get('count')} "
                     f"mean={cms.get('mean')} max={cms.get('max')}")
    if m["peak_bytes"]:
        mb = 1.0 / (1 << 20)
        tag_txt = "  ".join(f"{t}={b * mb:.2f}MB"
                            for t, b in sorted(m["tags"].items()))
        lines.append(f"device memory: live={m['live_bytes'] * mb:.2f}MB "
                     f"peak={m['peak_bytes'] * mb:.2f}MB"
                     + (f"  [{tag_txt}]" if tag_txt else ""))
    step_txt = f"steps: {s['train_steps_total']}"
    if s.get("train_step_ms"):
        h = s["train_step_ms"]
        step_txt += (f"  step_ms: mean={h.get('mean')} p99={h.get('p99')} "
                     f"max={h.get('max')}")
    if s.get("input_stall_pct") is not None:
        step_txt += f"  input_stall: {s['input_stall_pct']:.2f}%"
    lines.append(step_txt)
    n = report.get("numerics") or {}
    if n.get("grad_norm") is not None:
        num_txt = (f"numerics: grad_norm={n['grad_norm']:.4g} "
                   f"param_norm={n['param_norm']:.4g} "
                   f"update_ratio={n['update_ratio']:.4g} "
                   f"max_abs_grad={n['max_abs_grad']:.4g}")
        if n.get("nonfinite_steps") or n.get("nan_skips"):
            num_txt += (f"  nonfinite_steps={n['nonfinite_steps']} "
                        f"nan_skips={n['nan_skips']}")
        if n.get("grad_anomalies"):
            num_txt += f"  grad_anomalies={n['grad_anomalies']}"
        if n.get("observe_errors"):
            num_txt += f"  (observe errors: {n['observe_errors']})"
        lines.append(num_txt)
        prov = n.get("provenance")
        if prov is not None:
            lines.append(f"  provenance: expected={prov.get('expected')} "
                         f"got={prov.get('got')} "
                         f"ok={bool(prov.get('ok'))}")
        leaves = n.get("per_leaf_grad_norm") or {}
        if leaves:
            top = sorted(leaves.items(), key=lambda kv: -abs(kv[1]
                         if kv[1] == kv[1] else float("inf")))[:5]
            lines.append("  top leaf grad norms: "
                         + "  ".join(f"{k}={v:.4g}" for k, v in top))
    bl = report.get("blame")
    if bl:
        shares = bl.get("shares") or {}
        per = bl.get("per_step_ms") or {}
        parts = "  ".join(
            f"{c}={100.0 * shares.get(c, 0.0):.1f}%"
            f"({per.get(c, 0.0):.2f}ms)"
            for c in sorted(shares, key=lambda c: -shares[c])
            if shares.get(c, 0.0) > 0)
        lines.append(f"blame ({bl.get('n_steps')} steps, top="
                     f"{bl.get('top_category')}): {parts}")
        if bl.get("unresolved_links"):
            lines.append(
                f"  UNRESOLVED LINKS: {bl['unresolved_links']}")
    if report.get("spans"):
        import trace_merge
        lines.append("-- span summary --")
        lines.append(trace_merge.format_summary(report["spans"]))
    if tripped:
        lines.append("TRIPPED:")
        lines += [f"  - {t}" for t in tripped]
    else:
        lines.append("healthy: no gate tripped")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# self-contained mini-train mode (the CI health lane)
# ---------------------------------------------------------------------------

def mini_train(n_steps: int, trace_dir: str, numerics: bool = False,
               nan_step: Optional[int] = None):
    """Run a traced, health-armed N-step mini train and return
    ``(monitor.snapshot(), provenance-or-None)``.  Fixed seeds and
    shapes: a healthy run compiles exactly once per jit site and trips
    zero detectors — which is precisely what the CI gate asserts.

    ``numerics=True`` arms the model-numerics plane (FLAGS_numerics +
    the grad-norm drift detectors) on a two-branch model — a dense
    head plus an independent ``aux_w * z`` branch — wrapped in
    ``ResilientTrainStep``.  ``nan_step=K`` additionally NaN-poisons
    ONLY the aux branch's input at step K (chaos ``train.step_grads``
    with ``payload_index``), so exactly one leaf's gradient goes
    non-finite: the returned provenance dict records whether the
    ``train.nan_skip`` flight event named that leaf (``aux_w``), the
    run must still finish on finite losses (skip-and-restore), and the
    grad-norm detector's baseline stays clean — the CI numerics lane's
    seeded-NaN leg."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.framework import chaos, health, monitor
    from paddle_tpu.framework import numerics as numerics_mod
    from paddle_tpu.framework.flags import get_flags, set_flags
    from paddle_tpu.framework.observability import flight, tracer
    from paddle_tpu.framework.resilient import ResilientTrainStep
    from paddle_tpu.jit import TrainStep

    for signal, kw in health.DEFAULT_SIGNALS.items():
        health.watch(signal, **dict(kw))
    saved_flags = get_flags("numerics")
    provenance = None
    tracer.enable(trace_dir, label="health_check")
    try:
        paddle.seed(0)
        rng = np.random.default_rng(0)
        if not numerics:
            net = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=net.parameters())
            step = TrainStep(net,
                             lambda m, x, y: ((m(x) - y) ** 2).mean(),
                             opt)
            x = paddle.to_tensor(rng.standard_normal((16, 8))
                                 .astype(np.float32))
            y = paddle.to_tensor(rng.standard_normal((16, 4))
                                 .astype(np.float32))
            losses = [float(step(x, y)) for _ in range(n_steps)]
            assert all(np.isfinite(losses)), \
                f"mini train diverged: {losses}"
            params = net.parameters()
        else:
            set_flags({"numerics": True})

            class _TwoBranch(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.fc = nn.Linear(8, 4)
                    self.aux_w = self.create_parameter(
                        [4], default_initializer=paddle.nn.initializer
                        .Constant(0.1))

                def forward(self, x, z):
                    return self.fc(x), (self.aux_w * z).sum()

            def loss_fn(m, x, z, y):
                out, aux = m(x, z)
                return ((out - y) ** 2).mean() + 1e-3 * aux

            net = _TwoBranch()
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=net.parameters())
            step = ResilientTrainStep(TrainStep(net, loss_fn, opt))
            x = paddle.to_tensor(rng.standard_normal((16, 8))
                                 .astype(np.float32))
            z = paddle.to_tensor(rng.standard_normal((4,))
                                 .astype(np.float32))
            y = paddle.to_tensor(rng.standard_normal((16, 4))
                                 .astype(np.float32))
            if nan_step is not None:
                # poison ONLY the aux branch's input (payload index 1 =
                # z): the NaN reaches exactly aux_w's gradient
                chaos.arm("train.step_grads", mode="nan",
                          nth=int(nan_step), n_times=1, payload_index=1)
            losses = [float(step(x, z, y)) for _ in range(n_steps)]
            assert np.isfinite(losses[-1]), \
                f"mini train did not recover: {losses[-5:]}"
            if nan_step is not None:
                skips = flight.recent(50, kind="train.nan_skip")
                got = skips[-1]["attrs"].get("first_bad_leaf") \
                    if skips else None
                # the drift detector must fire AT the poisoned step
                # too (a non-finite grad norm is an anomaly by
                # definition — Detector's z=inf rule)
                ga = int(monitor.get_stat(
                    "health_anomaly_grad_norm_total"))
                provenance = {"expected": "aux_w", "got": got,
                              "nan_skips": len(skips),
                              "grad_anomalies": ga,
                              "ok": bool(skips) and got == "aux_w"
                              and step.skipped_steps == 1 and ga >= 1}
            params = net.parameters()
        health.memory.sample(tags={
            "params": sum(int(p._data.nbytes) for p in params)})
    finally:
        tracer.disable()
        if numerics:
            set_flags(saved_flags)
            chaos.disarm("train.step_grads")
            numerics_mod.reset()
    return monitor.snapshot(), provenance


def mini_train_ps(n_steps: int, trace_dir: str):
    """PS-backed mini-train leg: the same decision surface as
    :func:`mini_train`, but the embedding rows live on an in-process
    ``PsServer`` reached over localhost TCP, so the run exercises (and
    records) real ``ps.rpc`` traffic — the observatory lane injects
    ``ps.rpc`` latency into this leg via ``FLAGS_chaos_spec``.  An
    injection armed from step 0 is a LEVEL SHIFT: the in-run detector's
    warmup adopts it (this run's gates stay green), and only the
    cross-run ledger compare (``tools/perf_report.py compare``) can see
    it — which is exactly what that lane proves.  Deterministic: fixed
    seeds, fixed shapes, sync mode, no prefetch."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                           HostEmbeddingTable,
                                           PSTrainStep)
    from paddle_tpu.distributed.ps.service import (PsClient, PsServer,
                                                   RemoteEmbeddingTable)
    from paddle_tpu.framework import health, monitor
    from paddle_tpu.framework.observability import tracer

    from paddle_tpu.models import WideDeepHost

    for signal, kw in health.DEFAULT_SIGNALS.items():
        health.watch(signal, **dict(kw))
    tracer.enable(trace_dir, label="health_check_ps")
    table = HostEmbeddingTable(256, 9, optimizer="sgd",
                               learning_rate=0.05, seed=0)
    srv = PsServer({"emb": table}, port=0).start()
    cli = PsClient([f"127.0.0.1:{srv.port}"], wire_dtype="f32",
                   backoff_base=0.01)
    try:
        paddle.seed(0)
        emb = DistributedEmbedding(
            256, 9, mode="sync",
            table=RemoteEmbeddingTable(cli, "emb", 9))
        model = WideDeepHost(embedding_dim=8, num_fields=4, dense_dim=3,
                             hidden=(16,))
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())

        def loss_fn(m, rows, x, y):
            return F.binary_cross_entropy_with_logits(
                m(rows, x), y).mean()

        step = PSTrainStep(model, loss_fn, opt, emb,
                           transfer_dtype="float32", prefetch_depth=0)
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 256,
                           size=(n_steps, 8, 4)).astype(np.int64)
        x = paddle.to_tensor(rng.standard_normal((8, 3))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.random((8, 1)).astype(np.float32))
        losses = [float(step(ids[n], x, y)) for n in range(n_steps)]
        assert all(np.isfinite(losses)), \
            f"PS mini train diverged: {losses[-5:]}"
        step.flush()
    finally:
        try:
            cli.bye()
        finally:
            srv.shutdown()
            tracer.disable()
    return monitor.snapshot(), None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="health_check.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--metrics", default=None,
                    help="metrics snapshot: monitor.snapshot() JSON or "
                         "Prometheus text (MetricsReporter output)")
    ap.add_argument("--trace-dir", default=None,
                    help="directory of trace_*.jsonl span files "
                         "(adds the per-span summary to the report)")
    ap.add_argument("--mini-train", type=int, default=None, metavar="N",
                    help="self-contained mode: run a traced, "
                         "health-armed N-step mini train and evaluate "
                         "its own snapshot (the CI health lane)")
    ap.add_argument("--numerics", action="store_true",
                    help="mini-train option: arm the model-numerics "
                         "plane (FLAGS_numerics + grad-norm drift "
                         "detectors) on a two-branch model under "
                         "ResilientTrainStep")
    ap.add_argument("--nan-step", type=int, default=None, metavar="K",
                    help="mini-train option (implies --numerics): NaN-"
                         "poison only the aux branch's input at step K "
                         "and gate that train.nan_skip names that "
                         "branch's leaf as first_bad_leaf (the CI "
                         "numerics lane's seeded-NaN leg)")
    ap.add_argument("--ps", action="store_true",
                    help="mini-train option: run the PS-backed leg "
                         "(in-process PsServer over localhost TCP) so "
                         "real ps.rpc traffic feeds the detectors and "
                         "the run record")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="append a RunRecord (runlog.capture) for this "
                         "mini train to the run ledger at PATH — the "
                         "perf observatory's producer hook")
    ap.add_argument("--run-label", default=None,
                    help="RunRecord label (default: 'ps' or 'dense' "
                         "per the mini-train variant)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--max-anomalies", type=int, default=0,
                    help="gate: tolerated health_anomalies_total "
                         "(default 0)")
    ap.add_argument("--max-steady-recompiles", type=int, default=0,
                    help="gate: tolerated post-warmup recompiles "
                         "(default 0)")
    ap.add_argument("--max-input-stall", type=float, default=None,
                    help="gate: tolerated input_stall_pct (off by "
                         "default)")
    ap.add_argument("--max-grad-anomalies", type=int, default=None,
                    help="gate: tolerated grad-norm detector anomalies "
                         "(health_anomaly_grad_norm_total; off by "
                         "default)")
    ap.add_argument("--max-blame", action="append", default=None,
                    metavar="CATEGORY=PCT",
                    help="gate (repeatable): tolerated blame share per "
                         "category from the causal critical-path "
                         "analysis, e.g. --max-blame ps_wait=30 — "
                         "requires a trace (mini-train or "
                         "--trace-dir); categories: compute, ps_wait, "
                         "ingest_wait, collective, compile, other")
    a = ap.parse_args(argv)
    try:
        max_blame = parse_max_blame(a.max_blame)
    except ValueError as e:
        ap.error(str(e))
    if a.metrics is None and a.mini_train is None:
        ap.error("nothing to check: pass --metrics or --mini-train")
    if a.metrics is not None and a.mini_train is not None:
        ap.error("--metrics and --mini-train are mutually exclusive: "
                 "the mini train evaluates its own fresh snapshot")
    if a.nan_step is not None:
        a.numerics = True
    if a.numerics and a.mini_train is None:
        ap.error("--numerics/--nan-step are mini-train options")
    if a.ps and a.mini_train is None:
        ap.error("--ps is a mini-train option")
    if a.ps and a.numerics:
        ap.error("--ps and --numerics/--nan-step are separate "
                 "mini-train legs — run them as two invocations")
    if a.ledger is not None and a.mini_train is None:
        ap.error("--ledger records a mini train; pass --mini-train")

    health_snapshot = None
    provenance = None
    if a.mini_train is not None:
        if a.trace_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="health_check_")
            a.trace_dir = tmp.name          # kept alive by the local ref
        if a.ps:
            snap, provenance = mini_train_ps(a.mini_train, a.trace_dir)
        else:
            snap, provenance = mini_train(a.mini_train, a.trace_dir,
                                          numerics=a.numerics,
                                          nan_step=a.nan_step)
        from paddle_tpu.framework import health
        health_snapshot = health.snapshot()
    else:
        snap = load_metrics(a.metrics)

    report = build_report(snap, trace_dir=a.trace_dir,
                          health_snapshot=health_snapshot)
    if provenance is not None:
        report["numerics"]["provenance"] = provenance
    tripped = evaluate_gates(
        report, max_anomalies=a.max_anomalies,
        max_steady_recompiles=a.max_steady_recompiles,
        max_input_stall=a.max_input_stall,
        max_grad_anomalies=a.max_grad_anomalies,
        max_blame=max_blame)
    report["tripped"] = tripped
    if a.ledger is not None:
        # one RunRecord per mini train, appended AFTER the gates ran so
        # the verdict rides along; RunLedger.append never raises
        from paddle_tpu.framework import runlog
        label = a.run_label or ("ps" if a.ps else
                                "numerics" if a.numerics else "dense")
        rec = runlog.capture("health_check", label=label,
                             trace_dir=a.trace_dir,
                             blame_result=report.get("blame"),
                             extra={"steps": a.mini_train,
                                    "tripped": tripped})
        runlog.RunLedger(a.ledger).append(rec)
    if a.format == "json":
        print(json.dumps(report, indent=1, default=str))
    else:
        print(format_report(report, tripped))
    return 1 if tripped else 0


if __name__ == "__main__":
    sys.exit(main())
