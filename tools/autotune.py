#!/usr/bin/env python
"""Offline knob search over the run ledger — the autopilot's other half.

``framework/autopilot.py`` reacts at runtime; this tool looks backwards:
it replays measured evidence — ``kind="autotune"`` ledger records (its
own ``--measure`` mode appends them) plus, optionally, a
``perf_report attribute`` profile for a corroborating steady step time —
to search the knob space the runtime controller also drives
(``prefetch_depth`` × ``wire_dtype`` × ``batch_size``) against a
measured objective (mean steady step ms, lower is better), and emits a
**tuned profile**:

    {"schema_version": 1,
     "objective": {"signal": "step_ms_mean", "value": 3.2},
     "knobs": {"prefetch_depth": 2, "wire_dtype": "bf16",
               "batch_size": 8},
     "candidates": [...]}

``TrainStep`` / ``PSTrainStep`` / ``bench.py`` consume it at startup via
``FLAGS_autotune_profile`` →
:func:`paddle_tpu.framework.autopilot.maybe_apply_tuned_profile`, so a
run starts from the tuned operating point instead of defaults.

Modes::

    # measure: run a short PS mini-train per knob combo, append one
    # kind="autotune" record each to the ledger
    python tools/autotune.py --ledger runs.jsonl --measure --steps 24 \
        --grid "prefetch_depth=0,1,2;wire_dtype=f32,bf16;batch_size=8"

    # search: pick the best measured combo, write the tuned profile
    python tools/autotune.py --ledger runs.jsonl --out tuned.json

Measurements go into each record's ``extra`` (NOT ``summary``), so
``perf_report compare`` over the same ledger never mistakes a knob
sweep for a regression.  Deterministic: fixed seeds and shapes; the
per-combo mini-train is the ``health_check.mini_train_ps`` recipe with
the knobs applied.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

DEFAULT_GRID = "prefetch_depth=0,1,2;wire_dtype=f32,bf16;batch_size=8"
WARMUP_STEPS = 3          # compile-carrying steps excluded from timing


def parse_grid(spec: str) -> List[Dict[str, Any]]:
    """``"a=1,2;b=x,y"`` → the cross product as knob dicts (ints where
    they parse, strings otherwise), in deterministic order."""
    axes: List[Tuple[str, List[Any]]] = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        name, _, vals = part.partition("=")
        parsed: List[Any] = []
        for v in filter(None, (v.strip() for v in vals.split(","))):
            try:
                parsed.append(int(v))
            except ValueError:
                parsed.append(v)
        if not parsed:
            raise ValueError(f"empty grid axis: {part!r}")
        axes.append((name.strip(), parsed))
    combos: List[Dict[str, Any]] = [{}]
    for name, vals in axes:
        combos = [dict(c, **{name: v}) for c in combos for v in vals]
    return combos


def knob_key(knobs: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in knobs.items()))


# -- measure: one deterministic PS mini-train per combo ------------------

def measure_combo(knobs: Dict[str, Any], n_steps: int) -> Dict[str, Any]:
    """Run the fixed-seed PS mini-train under ``knobs`` and return its
    step-time stats.  Per-step wall times come from a local
    ``perf_counter`` ring (cumulative monitor counters would carry the
    previous combo's history)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import optimizer
    from paddle_tpu.distributed.ps import (DistributedEmbedding,
                                           HostEmbeddingTable,
                                           PSTrainStep)
    from paddle_tpu.distributed.ps.service import (PsClient, PsServer,
                                                   RemoteEmbeddingTable)

    pd = int(knobs.get("prefetch_depth", 0))
    wd = str(knobs.get("wire_dtype", "f32"))
    bs = int(knobs.get("batch_size", 8))

    table = HostEmbeddingTable(256, 9, optimizer="sgd",
                               learning_rate=0.05, seed=0)
    srv = PsServer({"emb": table}, port=0).start()
    cli = PsClient([f"127.0.0.1:{srv.port}"], wire_dtype=wd,
                   backoff_base=0.01)
    try:
        paddle.seed(0)
        emb = DistributedEmbedding(
            256, 9, mode="sync",
            table=RemoteEmbeddingTable(cli, "emb", 9))
        from paddle_tpu.models import WideDeepHost
        model = WideDeepHost(embedding_dim=8, num_fields=4, dense_dim=3,
                             hidden=(16,))
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())

        def loss_fn(m, rows, x, y):
            return F.binary_cross_entropy_with_logits(
                m(rows, x), y).mean()

        step = PSTrainStep(model, loss_fn, opt, emb,
                           transfer_dtype="float32", prefetch_depth=pd)
        rng = np.random.default_rng(3)
        ids = rng.integers(0, 256,
                           size=(n_steps, bs, 4)).astype(np.int64)
        x = paddle.to_tensor(rng.standard_normal((bs, 3))
                             .astype(np.float32))
        y = paddle.to_tensor(rng.random((bs, 1)).astype(np.float32))
        times: List[float] = []
        losses: List[float] = []
        for n in range(n_steps):
            if pd > 0 and n + 1 < n_steps:
                step.prefetch(ids[n + 1])
            t0 = time.perf_counter()
            losses.append(float(step(ids[n], x, y)))
            times.append((time.perf_counter() - t0) * 1e3)
        step.flush()
        assert all(np.isfinite(losses)), \
            f"autotune mini train diverged under {knobs}: {losses[-5:]}"
    finally:
        try:
            cli.bye()
        finally:
            srv.shutdown()
    steady = times[WARMUP_STEPS:] or times
    return {"step_ms_mean": statistics.fmean(steady),
            "step_ms_p90": sorted(steady)[
                max(0, int(0.9 * len(steady)) - 1)],
            "steps": len(steady)}


def measure(ledger_path: str, grid: List[Dict[str, Any]],
            n_steps: int) -> List[dict]:
    from paddle_tpu.framework import runlog
    ledger = runlog.RunLedger(ledger_path)
    out = []
    for knobs in grid:
        stats = measure_combo(knobs, n_steps)
        label = "-".join(f"{k}{v}" for k, v in sorted(knobs.items()))
        rec = {"schema_version": runlog.SCHEMA_VERSION,
               "kind": "autotune", "label": label,
               "run_id": runlog._run_id(), "ts": time.time(),
               "meta": runlog.run_meta(),
               # measurements live in extra, NOT summary: a knob sweep
               # must never register as a perf_report regression series
               "summary": {},
               "extra": {"knobs": knobs, **stats}}
        ledger.append(rec)
        out.append(rec)
        print(f"measured {label}: "
              f"{stats['step_ms_mean']:.2f} ms/step "
              f"(p90 {stats['step_ms_p90']:.2f}, "
              f"n={stats['steps']})")
    return out


# -- search: replay the ledger, pick the argmin combo --------------------

def search(records: List[dict],
           attribute_profile: Optional[dict] = None) -> dict:
    """Group ``kind="autotune"`` records by knob combo, score each by
    the median of its measured ``step_ms_mean`` (median across repeat
    sweeps rejects a one-off noisy run), and emit the tuned profile for
    the argmin."""
    groups: Dict[Tuple, Dict[str, Any]] = {}
    for r in records:
        if r.get("kind") != "autotune":
            continue
        extra = r.get("extra") or {}
        knobs = extra.get("knobs")
        mean = extra.get("step_ms_mean")
        if not isinstance(knobs, dict) or mean is None:
            continue
        g = groups.setdefault(knob_key(knobs),
                              {"knobs": knobs, "means": []})
        g["means"].append(float(mean))
    if not groups:
        raise SystemExit(
            "autotune: no kind=autotune records with measurements in "
            "the ledger — run --measure first")
    candidates = sorted(
        ({"knobs": g["knobs"], "runs": len(g["means"]),
          "step_ms_mean": statistics.median(g["means"])}
         for g in groups.values()),
        key=lambda c: c["step_ms_mean"])
    best = candidates[0]
    prof = {"schema_version": 1,
            "objective": {"signal": "step_ms_mean",
                          "value": round(best["step_ms_mean"], 4)},
            "knobs": dict(best["knobs"]),
            "candidates": [
                {"knobs": c["knobs"], "runs": c["runs"],
                 "step_ms_mean": round(c["step_ms_mean"], 4)}
                for c in candidates]}
    if attribute_profile:
        # corroboration, not an input to the argmin: the attribute
        # profile's steady step mean for the UNtuned program, so a
        # reader can see what the tuning is up against
        for row in attribute_profile.get("spans") or []:
            if row.get("name") == attribute_profile.get(
                    "step_span", "train.step"):
                prof["objective"]["attribute_step_ms"] = row.get("mean_ms")
    return prof


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="autotune.py",
                                 description=__doc__)
    ap.add_argument("--ledger", required=True,
                    help="run ledger (runlog JSONL) to measure into / "
                    "search over")
    ap.add_argument("--measure", action="store_true",
                    help="run one PS mini-train per grid combo and "
                    "append kind=autotune records")
    ap.add_argument("--steps", type=int, default=24,
                    help="mini-train steps per combo (default 24)")
    ap.add_argument("--grid", default=DEFAULT_GRID,
                    help=f"knob grid (default {DEFAULT_GRID!r})")
    ap.add_argument("--attribute", default=None, metavar="PROF_JSON",
                    help="perf_report attribute profile: its steady "
                    "step mean is recorded in the output objective as "
                    "corroboration")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the tuned profile here (search phase; "
                    "omit to only measure)")
    a = ap.parse_args(argv)

    if a.measure:
        measure(a.ledger, parse_grid(a.grid), a.steps)
    if a.out is None:
        return 0

    from paddle_tpu.framework import runlog
    records = runlog.RunLedger(a.ledger).read()
    attr = None
    if a.attribute:
        with open(a.attribute, "r", encoding="utf-8") as f:
            attr = json.load(f)
    prof = search(records, attr)
    with open(a.out, "w", encoding="utf-8") as f:
        json.dump(prof, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"tuned profile -> {a.out}")
    print(f"  objective step_ms_mean="
          f"{prof['objective']['value']:.3f}")
    print(f"  knobs {prof['knobs']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
