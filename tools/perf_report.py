#!/usr/bin/env python
"""Continuous-perf observatory CLI: span<->cost attribution and
cross-run regression detection over the persistent run ledger.

Three subcommands close the measure -> remember -> decide loop the
run ledger (``paddle_tpu/framework/runlog.py``) records for:

* ``attribute`` — join a merged trace's per-span-name aggregates
  (``tools/trace_merge.py summarize``) with the PTA106 analytic
  FLOP/byte cost model (``TrainStep.analyze()``) into a measured
  op-profile: per span name count / mean / p99 ms, and for the step
  program an achieved FLOP/s + bytes/s against the analytic totals,
  with the top-k PTA106 ops carrying a measured ms attributed from the
  step span by flop share.  Emitted as JSON (the autotune input) and a
  roofline-style text table.  ``--mini-train N`` is the self-contained
  form (traced N-step train + ``analyze()`` in-process); ``--trace-dir
  + --cost-json`` joins existing artifacts.  ``--check`` gates that
  every top-k op has a positive measured ms and a finite achieved
  FLOP/s (the CI lane's acceptance).

* ``compare`` — run the existing ``health.Detector`` (EWMA + robust
  MAD z-score, deterministic, floor-protected) over ledger series:
  step-time p99, RPC p99, input stall, compile counts, anomaly totals
  (from each record's ``summary``) and every bench-leg metric (from
  ``legs``).  Series form within one ``(kind, label)`` record group.
  Short ledgers still gate: the pre-candidate prefix is cycled through
  the detector's warmup (MAD collapses to 0 on replicated values — the
  ``min_mad``/``rel_floor`` floors are exactly what keeps that sound),
  then every post-warmup run is scored.  Anomalies in the signal's
  WORSE direction are regressions (named, nonzero exit);
  better-direction anomalies are reported as improvements.

* ``blame`` — causal critical-path attribution
  (``framework/blame.py``): rebuild the per-step dependency DAG from a
  trace's span links (prefetch -> step, ingest fetch -> step, deferred
  push -> push_pull RPC), collapse the critical path into a per-step
  blame vector over fixed categories (``compute`` / ``ps_wait`` /
  ``ingest_wait`` / ``collective`` / ``compile`` / ``other``), and
  report totals, shares and the top blocking edges.  ``--check`` gates
  that every link resolves and the categories sum to within tolerance
  of the measured step span; ``--expect-top ps_wait`` is the chaos
  leg's assertion that injected RPC latency moved the bottleneck.
  ``compare`` detects the same categories cross-run
  (``blame_<cat>_ms`` series from each record's summary), so a
  bottleneck SHIFT at flat step time is a named regression.

* ``import`` — fold historical driver ``BENCH_r*.json`` artifacts into
  a ledger as ``imported_bench`` records, so the bench trajectory
  becomes a first-class compare series.

* ``incidents`` — the postmortem plane's index: list ``kind=incident``
  ledger records (one per auto-captured bundle —
  ``framework/incident.py``) joined by incident id with the
  ``kind=incident_replay`` verdicts ``tools/replay.py --ledger``
  writes back, so reproduced-vs-not (and the bisected divergence
  step) reads next to each capture.

Usage::

    python tools/perf_report.py attribute --mini-train 3 --json prof.json --check
    python tools/perf_report.py attribute --trace-dir /tmp/tr --cost-json cost.json
    python tools/perf_report.py blame --mini-train 12 --check
    python tools/perf_report.py blame --trace-dir /tmp/tr --expect-top ps_wait
    python tools/perf_report.py compare --ledger runs/ledger.jsonl
    python tools/perf_report.py import BENCH_r0*.json --ledger runs/hist.jsonl
    python tools/perf_report.py incidents --ledger runs/ledger.jsonl --json inc.json
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
import tempfile
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

__all__ = ["attribute_profile", "format_attribute", "check_profile",
           "mini_train_cost", "leg_signal_cfg", "SUMMARY_SIGNAL_CFG",
           "build_series", "detect_series", "compare_records",
           "format_compare", "incident_rows", "format_incidents",
           "main"]


# ---------------------------------------------------------------------------
# attribute: span <-> cost-model join
# ---------------------------------------------------------------------------

def attribute_profile(rows: List[dict], cost: Optional[dict],
                      step_span: str = "train.step",
                      top_k: int = 5) -> dict:
    """Join trace-summary rows (``trace_merge.summarize``) with a
    structured PTA106 cost report (``Report.cost``) into the measured
    op-profile.  ``step_span`` names the span that executes the costed
    program (one span == one dispatch of it); the top-k cost ops get a
    measured ms attributed from that span's STEADY mean — the single
    heaviest span, i.e. the compile-carrying first dispatch, excluded —
    by flop share (an in-program attribution, honest about being a
    model — the ``attribution`` field says so)."""
    # Flop-share attribution makes each op's achieved FLOP/s equal the
    # PROGRAM rate by construction (flops_i / (mean_ms * flops_i /
    # total) == total / mean_ms) — it is the roofline sanity value the
    # acceptance gate checks for finiteness, not a per-op measurement.
    # The per-op information lives in measured_ms (the time share) and
    # achieved_bytes_per_sec (which DOES vary with each op's byte/flop
    # mix); true per-op rates need per-op spans, which XLA fusion
    # erases anyway.
    spans = {r["name"]: r for r in rows}
    prof: Dict[str, object] = {"schema_version": 1,
                               "step_span": step_span,
                               "spans": rows, "cost": cost, "ops": []}
    step = spans.get(step_span)
    if step is None or not cost:
        return prof
    # steady-state step time: drop the single heaviest span from the
    # mean — the first dispatch carries the XLA compile (hundreds of
    # ms vs sub-ms steps) and would inflate every attributed ms by
    # orders of magnitude.  One span only: nothing to drop.
    count = int(step["count"])
    raw_mean = float(step["mean_ms"])
    if count > 1:
        mean_ms = (float(step["total_ms"]) - float(step["max_ms"])) \
            / (count - 1)
    else:
        mean_ms = raw_mean
    sec = mean_ms / 1e3
    total_f = int(cost.get("total_flops", 0))
    total_b = int(cost.get("total_bytes", 0))
    prof["step"] = {
        "span": step_span,
        "count": count,
        "mean_ms": round(mean_ms, 6),
        "mean_ms_with_compile": raw_mean,
        "p99_ms": step["p99_ms"],
        "flops_per_step": total_f,
        "bytes_per_step": total_b,
        "achieved_flops_per_sec": total_f / sec if sec > 0 else None,
        "achieved_bytes_per_sec": total_b / sec if sec > 0 else None,
        "arithmetic_intensity": (total_f / total_b) if total_b else None,
    }
    ranked = [o for o in cost.get("by_op", []) if o.get("flops", 0) > 0]
    ops = []
    for rank, o in enumerate(ranked[:max(0, int(top_k))], start=1):
        share = o["flops"] / total_f if total_f else 0.0
        ms = mean_ms * share
        ops.append({
            "rank": rank, "op": o["op"], "count": o.get("count", 0),
            "flops": int(o["flops"]), "bytes": int(o.get("bytes", 0)),
            "flop_share": round(share, 4),
            "measured_ms": round(ms, 6),
            "achieved_flops_per_sec":
                o["flops"] / (ms / 1e3) if ms > 0 else None,
            "achieved_bytes_per_sec":
                o.get("bytes", 0) / (ms / 1e3) if ms > 0 else None,
            "attribution": "flop_share",
        })
    prof["ops"] = ops
    return prof


def check_profile(prof: dict, top_k: int = 5) -> List[str]:
    """The acceptance gate: the joined profile must carry a step row and
    top-k op rows whose measured ms is positive and achieved FLOP/s
    finite.  Returns the list of violations (empty = pass)."""
    bad = []
    step = prof.get("step")
    if not step:
        bad.append(f"no step row: span {prof.get('step_span')!r} absent "
                   "from the trace or no cost report joined")
        return bad
    ops = prof.get("ops") or []
    if not ops:
        bad.append("no op rows: cost report has no op with flops > 0")
    for o in ops[:top_k]:
        ms = o.get("measured_ms")
        fps = o.get("achieved_flops_per_sec")
        if not ms or ms <= 0:
            bad.append(f"op {o['op']!r}: no measured ms ({ms!r})")
        if fps is None or not math.isfinite(float(fps)):
            bad.append(f"op {o['op']!r}: achieved FLOP/s not finite "
                       f"({fps!r})")
    return bad


def _human(v: Optional[float], unit: str = "") -> str:
    if v is None:
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                          (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}{unit}"
    return f"{v:.2f}{unit}"


def format_attribute(prof: dict) -> str:
    """Render the joined profile as a roofline-style text table."""
    lines = ["== op profile (measured spans x PTA106 analytic cost) =="]
    step = prof.get("step")
    if step:
        ai = step["arithmetic_intensity"]
        lines.append(
            f"step span {step['span']!r}: {step['count']} x "
            f"mean {step['mean_ms']:.3f} ms (p99 {step['p99_ms']:.3f}) | "
            f"{_human(float(step['flops_per_step']))}flop "
            f"{_human(float(step['bytes_per_step']))}B per step | "
            f"achieved {_human(step['achieved_flops_per_sec'])}FLOP/s "
            f"{_human(step['achieved_bytes_per_sec'])}B/s | "
            f"intensity {'-' if ai is None else round(ai, 2)} flop/B")
    ops = prof.get("ops") or []
    if ops:
        cols = ("#", "op", "count", "flops", "bytes", "ms",
                "FLOP/s", "B/s", "share")
        table = [cols]
        for o in ops:
            table.append((str(o["rank"]), o["op"], str(o["count"]),
                          _human(float(o["flops"])),
                          _human(float(o["bytes"])),
                          f"{o['measured_ms']:.4f}",
                          _human(o["achieved_flops_per_sec"]),
                          _human(o["achieved_bytes_per_sec"]),
                          f"{o['flop_share']:.1%}"))
        widths = [max(len(r[i]) for r in table)
                  for i in range(len(cols))]
        for j, row in enumerate(table):
            lines.append("  ".join(
                c.ljust(widths[i]) if i == 1 else c.rjust(widths[i])
                for i, c in enumerate(row)))
            if j == 0:
                lines.append("  ".join("-" * w for w in widths))
    rows = prof.get("spans") or []
    if rows:
        import trace_merge
        lines.append("-- span summary --")
        lines.append(trace_merge.format_summary(rows))
    return "\n".join(lines)


def mini_train_cost(n_steps: int, trace_dir: str) -> dict:
    """Self-contained attribute input: run a traced, fixed-seed N-step
    mini train (two-layer MLP under ``TrainStep``) whose ``train.step``
    spans land in ``trace_dir``, then ``analyze()`` the same step for
    the structured PTA106 cost report.  Returns ``Report.cost``."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.framework.observability import tracer
    from paddle_tpu.jit import TrainStep

    class _MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(32, 64)
            self.fc2 = nn.Linear(64, 8)

        def forward(self, x):
            return self.fc2(
                paddle.nn.functional.relu(self.fc1(x)))

    paddle.seed(0)
    rng = np.random.default_rng(0)
    net = _MLP()
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    step = TrainStep(net, lambda m, x, y: ((m(x) - y) ** 2).mean(), opt)
    x = paddle.to_tensor(rng.standard_normal((16, 32)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32))
    tracer.enable(trace_dir, label="perf_report")
    try:
        for _ in range(n_steps):
            step(x, y)
    finally:
        tracer.disable()
    report = step.analyze(x, y)
    return report.cost


# ---------------------------------------------------------------------------
# compare: Detector over ledger series
# ---------------------------------------------------------------------------

#: per-signal detector shape for the scalar summary series each record
#: carries.  ``worse`` names the regression direction; the floors keep
#: replicated-baseline MAD collapse (short ledgers) from flagging
#: jitter — latency needs tens of ms or a multiple of baseline, counts
#: need a jump of several
SUMMARY_SIGNAL_CFG: Dict[str, dict] = {
    "train_step_p99_ms": {"worse": "up", "min_mad": 5.0,
                          "rel_floor": 0.5},
    "train_step_mean_ms": {"worse": "up", "min_mad": 5.0,
                           "rel_floor": 0.5},
    "ps_rpc_p99_ms": {"worse": "up", "min_mad": 5.0, "rel_floor": 0.5},
    "ps_rpc_mean_ms": {"worse": "up", "min_mad": 5.0, "rel_floor": 0.5},
    "input_stall_pct": {"worse": "up", "min_mad": 2.0,
                        "rel_floor": 0.25},
    "jit_compiles_total": {"worse": "up", "min_mad": 0.5,
                           "z_threshold": 6.0},
    "jit_recompiles_steady_total": {"worse": "up", "min_mad": 0.1,
                                    "z_threshold": 6.0},
    "health_anomalies_total": {"worse": "up", "min_mad": 0.5,
                               "z_threshold": 6.0},
    "numerics_nonfinite_steps_total": {"worse": "up", "min_mad": 0.1,
                                       "z_threshold": 6.0},
    # ZeRO collective wire bytes per step (parallel/zero.py via
    # monitor stats): deterministic byte accounting for the fused
    # reduce-scatter + all-gather pair, so a wire/codec change shows
    # up as a named byte-series move — a quantized ring run against an
    # f32 baseline prints an IMPROVEMENT here, a silently-widened wire
    # a regression.  Bytes are exact (no timing jitter): tiny floors
    "zero_collective_bytes_per_step": {"worse": "up", "min_mad": 1.0,
                                       "rel_floor": 0.02},
    # cluster-granularity series (framework/collector.py
    # CollectorServer.capture_record): the collector's cross-worker
    # view gates here — a new straggler, a step-skew jump, or RPC-p99
    # growth across runs is a named regression
    "cluster_step_p99_ms_max": {"worse": "up", "min_mad": 5.0,
                                "rel_floor": 0.5},
    "cluster_ps_rpc_p99_ms": {"worse": "up", "min_mad": 5.0,
                              "rel_floor": 0.5},
    "cluster_input_stall_pct_max": {"worse": "up", "min_mad": 2.0,
                                    "rel_floor": 0.25},
    "cluster_step_skew": {"worse": "up", "min_mad": 0.5,
                          "z_threshold": 6.0},
    "cluster_straggler_count": {"worse": "up", "min_mad": 0.4,
                                "z_threshold": 6.0},
    "cluster_anomalies_total": {"worse": "up", "min_mad": 0.5,
                                "z_threshold": 6.0},
    "cluster_report_gaps_total": {"worse": "up", "min_mad": 2.0,
                                  "rel_floor": 0.5},
    # per-step blame series (framework/blame.py via runlog.capture):
    # a run whose TOTAL step time is flat but whose blame shifted —
    # compute fell, ps_wait rose — is a bottleneck shift, flagged by
    # the category name.  Every category regresses UP (more blocked ms
    # per step is worse whatever the resource); floors keep sub-ms
    # localhost jitter quiet while an injected latency (tens of ms)
    # clears them by an order of magnitude
    "blame_compute_ms": {"worse": "up", "min_mad": 5.0,
                         "rel_floor": 0.5},
    "blame_ps_wait_ms": {"worse": "up", "min_mad": 2.0,
                         "rel_floor": 0.5},
    "blame_ingest_wait_ms": {"worse": "up", "min_mad": 2.0,
                             "rel_floor": 0.5},
    "blame_collective_ms": {"worse": "up", "min_mad": 2.0,
                            "rel_floor": 0.5},
    "blame_compile_ms": {"worse": "up", "min_mad": 10.0,
                         "rel_floor": 1.0},
    "blame_other_ms": {"worse": "up", "min_mad": 2.0,
                       "rel_floor": 0.5},
}


def leg_signal_cfg(metric: str, unit: Optional[str]) -> dict:
    """Detector shape for a bench-leg metric, inferred from its name
    and unit: throughput regresses DOWN, latency/bytes/stall UP."""
    m = metric.lower()
    u = (unit or "").lower()
    if "stall" in m or m.endswith("_pct"):
        return {"worse": "up", "min_mad": 2.0, "rel_floor": 0.25}
    if "per_sec" in m:
        return {"worse": "down", "min_mad": 1e-9, "rel_floor": 0.05,
                "z_threshold": 4.0}
    if u in ("ms", "s") or m.endswith("_ms"):
        return {"worse": "up", "min_mad": 5.0, "rel_floor": 0.5}
    if u in ("mb", "bytes") or "mb_per" in m or "bytes" in m:
        return {"worse": "up", "min_mad": 1e-9, "rel_floor": 0.05,
                "z_threshold": 4.0}
    if "agreement" in m or u == "fraction":
        return {"worse": "down", "min_mad": 0.02, "rel_floor": 0.05}
    return {"worse": "both", "min_mad": 1e-9, "rel_floor": 0.25}


def build_series(records: List[dict]) -> Dict[str, dict]:
    """Signal series over one (kind, label) record group: summary
    scalars (known shapes only) plus every bench-leg metric.  Each
    series is ``{"cfg", "points": [(record_index, value), ...]}`` —
    a record missing a signal simply contributes no point (the plane
    was off for that run, not at zero)."""
    series: Dict[str, dict] = {}

    def add(name, cfg, idx, value):
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        s = series.setdefault(name, {"cfg": cfg, "points": []})
        s["points"].append((idx, v))

    for i, rec in enumerate(records):
        for sig, v in (rec.get("summary") or {}).items():
            cfg = SUMMARY_SIGNAL_CFG.get(sig)
            if cfg is not None:
                add(sig, cfg, i, v)
        for leg in rec.get("legs") or []:
            m = leg.get("metric")
            v = leg.get("value")
            if not m or v is None:
                continue
            if "_FAILED" in m or "SKIPPED" in m or \
                    m == "device_unavailable":
                continue               # failure markers are not series
            add(f"bench:{m}", leg_signal_cfg(m, leg.get("unit")), i, v)
    return series


def detect_series(signal: str, points, cfg: dict,
                  warmup: int = 4) -> dict:
    """Score one ledger series with ``health.Detector``.  The
    pre-candidate prefix is cycled through the detector's warmup so a
    2-run ledger still gates its second run; every post-warmup
    observation is scored, each run at most once.  Deterministic: the
    injected zero clock keeps anomaly records value-only."""
    from paddle_tpu.framework.health import Detector

    cfg = dict(cfg)
    worse = cfg.pop("worse", "both")
    n = len(points)
    if n < 2:
        return {"signal": signal, "status": "insufficient", "n": n,
                "regressions": [], "improvements": []}
    warmup = max(4, int(warmup))
    det = Detector(signal, warmup=warmup, window=64,
                   max_consecutive=1 << 30, clock=lambda: 0.0, **cfg)
    base = points[:-1]
    reps = -(-warmup // len(base))     # ceil: fill the minimum baseline
    seq = []
    for _ in range(reps):
        seq.extend(base)
    seq.append(points[-1])
    seen = set()
    regressions, improvements = [], []
    for idx, v in seq:
        a = det.update(v)
        if a is None or idx in seen:
            continue
        seen.add(idx)
        nonfinite = not math.isfinite(a.value)
        up = a.value > a.median if not nonfinite else True
        item = {"signal": signal, "run_index": idx,
                "value": a.value if nonfinite else round(a.value, 6),
                "median": round(a.median, 6),
                "z": round(a.z, 3) if math.isfinite(a.z) else "inf",
                "direction": "nonfinite" if nonfinite
                else ("up" if up else "down")}
        if nonfinite:
            # a NaN/inf measurement is a regression on EVERY signal —
            # a blown-up throughput number must not route to
            # "improvements" just because its worse-direction is down
            regressions.append(item)
        elif worse == "both" or ("up" if up else "down") == worse:
            regressions.append(item)
        else:
            improvements.append(item)
    return {"signal": signal, "status": "ok", "n": n,
            "regressions": regressions, "improvements": improvements}


def _run_name(rec: dict, idx: int) -> str:
    return str(rec.get("run") or rec.get("run_id") or f"run[{idx}]")


def compare_records(records: List[dict], warmup: int = 4,
                    kind: Optional[str] = None,
                    label: Optional[str] = None) -> dict:
    """Group ledger records by (kind, label), build the signal series,
    and detect.  Returns the full verdict dict (``regressions`` is the
    gate: empty = healthy)."""
    groups: Dict[tuple, List[tuple]] = {}
    for rec in records:
        if kind is not None and rec.get("kind") != kind:
            continue
        if label is not None and rec.get("label") != label:
            continue
        key = (str(rec.get("kind")), str(rec.get("label") or ""))
        groups.setdefault(key, []).append(rec)
    out = {"schema_version": 1, "groups": [], "regressions": [],
           "improvements": [], "insufficient": []}
    for (k, lb), recs in sorted(groups.items()):
        series = build_series(recs)
        gr = {"kind": k, "label": lb, "runs": len(recs),
              "run_names": [_run_name(r, i) for i, r in enumerate(recs)],
              "signals": []}
        for sig in sorted(series):
            s = series[sig]
            res = detect_series(sig, s["points"], s["cfg"],
                                warmup=warmup)
            gr["signals"].append(res)
            gname = f"{k}/{lb}" if lb else k
            for item in res["regressions"]:
                out["regressions"].append(
                    {**item, "group": gname,
                     "run": _run_name(recs[item["run_index"]],
                                      item["run_index"])})
            for item in res["improvements"]:
                out["improvements"].append(
                    {**item, "group": gname,
                     "run": _run_name(recs[item["run_index"]],
                                      item["run_index"])})
            if res["status"] == "insufficient":
                out["insufficient"].append(
                    {"group": gname, "signal": sig, "n": res["n"]})
        out["groups"].append(gr)
    return out


def format_compare(result: dict) -> str:
    lines = ["== perf_report compare =="]
    for gr in result["groups"]:
        gname = f"{gr['kind']}/{gr['label']}" if gr["label"] \
            else gr["kind"]
        ok = sum(1 for s in gr["signals"]
                 if s["status"] == "ok" and not s["regressions"])
        lines.append(f"group {gname}: {gr['runs']} run(s), "
                     f"{len(gr['signals'])} signal(s), {ok} clean")
    for item in result["regressions"]:
        lines.append(
            f"REGRESSION {item['group']} {item['signal']}: "
            f"run {item['run']} value={item['value']} "
            f"median={item['median']} z={item['z']} "
            f"({item['direction']})")
    for item in result["improvements"]:
        lines.append(
            f"improvement {item['group']} {item['signal']}: "
            f"run {item['run']} value={item['value']} "
            f"median={item['median']} z={item['z']}")
    if result["insufficient"]:
        sigs = ", ".join(f"{i['group']}:{i['signal']}({i['n']})"
                         for i in result["insufficient"][:10])
        more = len(result["insufficient"]) - 10
        lines.append(f"insufficient data: {sigs}"
                     + (f" (+{more} more)" if more > 0 else ""))
    lines.append(f"verdict: {len(result['regressions'])} regression(s), "
                 f"{len(result['improvements'])} improvement(s)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _cmd_attribute(a) -> int:
    import trace_merge
    tmp = None
    cost = None
    if a.mini_train is not None and a.cost_json:
        print("perf_report attribute: --mini-train and --cost-json are "
              "mutually exclusive — the mini train analyzes its own "
              "step; joining a foreign cost model against its trace "
              "would gate the wrong program", file=sys.stderr)
        return 2
    if a.mini_train is not None:
        if a.trace_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="perf_report_")
            a.trace_dir = tmp.name
        cost = mini_train_cost(a.mini_train, a.trace_dir)
    elif a.cost_json:
        with open(a.cost_json) as f:
            doc = json.load(f)
        cost = doc.get("cost", doc) if isinstance(doc, dict) else None
    if a.trace_dir is None:
        print("perf_report attribute: need --mini-train or --trace-dir",
              file=sys.stderr)
        return 2
    paths = sorted(glob.glob(os.path.join(a.trace_dir,
                                          "trace_*.jsonl")))
    if not paths:
        print(f"perf_report attribute: no trace_*.jsonl under "
              f"{a.trace_dir}", file=sys.stderr)
        return 2
    rows = trace_merge.summarize(trace_merge.merge(paths))
    prof = attribute_profile(rows, cost, step_span=a.step_span,
                             top_k=a.top_k)
    if a.json:
        with open(a.json, "w") as f:
            json.dump(prof, f, indent=1, default=str)
    print(format_attribute(prof))
    if a.check:
        bad = check_profile(prof, top_k=a.top_k)
        if bad:
            for b in bad:
                print(f"CHECK FAILED: {b}", file=sys.stderr)
            return 1
        print(f"check ok: {len(prof.get('ops') or [])} op row(s) with "
              "measured ms and finite achieved FLOP/s")
    return 0


def _cmd_blame(a) -> int:
    from paddle_tpu.framework import blame
    tmp = None
    if a.mini_train is not None:
        if a.trace_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="perf_blame_")
            a.trace_dir = tmp.name
        import health_check
        health_check.mini_train_ps(a.mini_train, a.trace_dir)
    if a.trace_dir is None:
        print("perf_report blame: need --mini-train or --trace-dir",
              file=sys.stderr)
        return 2
    spans = blame.load_trace_dir(a.trace_dir)
    if not spans:
        print(f"perf_report blame: no trace_*.jsonl spans under "
              f"{a.trace_dir}", file=sys.stderr)
        return 2
    result = blame.compute_blame(spans, step_span=a.step_span)
    if a.json:
        with open(a.json, "w") as f:
            json.dump(result, f, indent=1, default=str)
    print(blame.format_blame(result))
    if a.check or a.expect_top:
        # the sum/link-integrity gates arm only under --check:
        # --expect-top alone must stay usable on input-stalled traces,
        # whose cycle legitimately exceeds their step-span total
        bad = blame.check(
            result, tolerance=a.tolerance if a.check else None,
            expect_top=a.expect_top)
        if bad:
            for b in bad:
                print(f"CHECK FAILED: {b}", file=sys.stderr)
            return 1
        parts = [f"check ok: {result['n_steps']} step(s)"]
        if a.check:
            blame_sum = sum(result["totals_ms"].values())
            parts.append(f"blame sum {blame_sum:.3f} ms vs step span "
                         f"total {result['span_ms_total']:.3f} ms, "
                         "0 unresolved links")
        if a.expect_top:
            parts.append(f"top category {result['top_category']}")
        print(", ".join(parts))
    return 0


def _cmd_compare(a) -> int:
    from paddle_tpu.framework.runlog import RunLedger
    records = RunLedger(a.ledger).read()
    if not records:
        print(f"perf_report compare: no readable records in {a.ledger}",
              file=sys.stderr)
        return 2
    result = compare_records(records, warmup=a.warmup, kind=a.kind,
                             label=a.label)
    if a.json:
        with open(a.json, "w") as f:
            json.dump(result, f, indent=1, default=str)
    print(format_compare(result))
    return 1 if len(result["regressions"]) > a.max_regressions else 0


def _cmd_import(a) -> int:
    from paddle_tpu.framework.runlog import (RunLedger,
                                             import_bench_file)
    ledger = RunLedger(a.ledger)
    imported = 0
    for path in a.files:
        rec = import_bench_file(path)
        if rec is None:
            print(f"perf_report import: {path}: no parseable bench "
                  "legs — skipped", file=sys.stderr)
            continue
        if ledger.append(rec):
            imported += 1
            print(f"imported {os.path.basename(path)}: "
                  f"{len(rec['legs'])} leg(s)")
    print(f"perf_report import: {imported}/{len(a.files)} file(s) -> "
          f"{a.ledger}")
    return 0 if imported else 1


def incident_rows(records: List[dict],
                  kind: Optional[str] = None) -> List[dict]:
    """Join ``kind=incident`` ledger records (the capture plane's index)
    with ``kind=incident_replay`` verdicts (``tools/replay.py
    --ledger``) by incident id: one row per captured incident carrying
    its trigger kind, step, first bad leaf, bundle path, and the latest
    replay/bisect outcome (``unreplayed`` when none landed yet)."""
    verdicts: Dict[Any, dict] = {}
    for rec in records:
        if rec.get("kind") != "incident_replay":
            continue
        v = rec.get("replay_verdict") or {}
        if v.get("id") is not None:
            verdicts[v["id"]] = v       # latest wins (ledger order)
    rows = []
    for rec in records:
        if rec.get("kind") != "incident":
            continue
        info = rec.get("incident") or {}
        if kind and info.get("kind") != kind:
            continue
        v = verdicts.get(info.get("id"))
        if v is None:
            replay = "unreplayed"
        elif v.get("mode") == "bisect":
            replay = (f"bisect:step={v.get('divergent_step')}"
                      f",leaf={v.get('leaf')}"
                      if v.get("divergent_step") is not None
                      else "bisect:clean")
        else:
            replay = "reproduced" if v.get("reproduced") \
                else "not_reproduced"
        rows.append({"id": info.get("id"), "kind": info.get("kind"),
                     "step": info.get("step"),
                     "first_bad_leaf": info.get("first_bad_leaf"),
                     "worker": info.get("worker"),
                     "bundle": info.get("bundle"),
                     "ts": rec.get("ts"), "replay": replay,
                     "verdict": v})
    return rows


def format_incidents(rows: List[dict]) -> str:
    lines = [f"== incidents: {len(rows)} captured =="]
    hdr = (("id", 4), ("kind", 22), ("step", 6), ("first_bad_leaf", 16),
           ("replay", 26), ("bundle", 0))
    lines.append("  ".join(n.ljust(w) for n, w in hdr))
    for r in rows:
        lines.append("  ".join([
            str(r.get("id", "?")).ljust(4),
            str(r.get("kind", "?"))[:22].ljust(22),
            str(r.get("step", "-")).ljust(6),
            str(r.get("first_bad_leaf") or "-")[:16].ljust(16),
            str(r.get("replay", "?"))[:26].ljust(26),
            str(r.get("bundle") or "-")]))
    return "\n".join(lines)


def _cmd_incidents(a) -> int:
    from paddle_tpu.framework.runlog import RunLedger
    records = RunLedger(a.ledger).read()
    rows = incident_rows(records, kind=a.kind)
    if a.json:
        with open(a.json, "w") as f:
            json.dump({"incidents": rows}, f, indent=1, default=str)
    print(format_incidents(rows))
    if not rows and not records:
        print(f"perf_report incidents: no readable records in "
              f"{a.ledger}", file=sys.stderr)
        return 2
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="perf_report.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)

    at = sub.add_parser("attribute",
                        help="join a merged trace with the PTA106 "
                             "cost model into a measured op-profile")
    at.add_argument("--mini-train", type=int, default=None, metavar="N",
                    help="self-contained mode: traced N-step mini "
                         "train + TrainStep.analyze() in-process")
    at.add_argument("--trace-dir", default=None,
                    help="directory of trace_*.jsonl span files")
    at.add_argument("--cost-json", default=None,
                    help="structured PTA106 cost report (Report.cost "
                         "shape, or a profile JSON carrying one under "
                         "'cost')")
    at.add_argument("--step-span", default="train.step",
                    help="span name that executes the costed program "
                         "(default: train.step)")
    at.add_argument("--top-k", type=int, default=5,
                    help="op rows to attribute (default 5)")
    at.add_argument("--json", default=None, metavar="PATH",
                    help="write the joined profile JSON here (the "
                         "autotune input)")
    at.add_argument("--check", action="store_true",
                    help="gate: every top-k op must have a positive "
                         "measured ms and finite achieved FLOP/s")

    bl = sub.add_parser("blame",
                        help="causal critical-path blame: rebuild the "
                             "per-step dependency DAG from a trace "
                             "(span links) and collapse it into "
                             "per-category blocked-time vectors")
    bl.add_argument("--trace-dir", default=None,
                    help="directory of trace_*.jsonl span files")
    bl.add_argument("--mini-train", type=int, default=None, metavar="N",
                    help="self-contained mode: run the PS-backed "
                         "traced N-step mini train "
                         "(tools/health_check.py mini_train_ps) and "
                         "blame its own trace")
    bl.add_argument("--step-span", default="train.step",
                    help="span name of the consuming step "
                         "(default: train.step)")
    bl.add_argument("--json", default=None, metavar="PATH",
                    help="write the full blame result JSON here")
    bl.add_argument("--check", action="store_true",
                    help="gate: steps found, every link resolves, "
                         "blame categories sum to within --tolerance "
                         "of the measured step span")
    bl.add_argument("--tolerance", type=float, default=0.05,
                    help="blame-sum vs step-span tolerance for "
                         "--check (default 0.05)")
    bl.add_argument("--expect-top", default=None, metavar="CATEGORY",
                    help="gate: the named category must carry the "
                         "largest blame share (the chaos leg's "
                         "ps_wait assertion)")

    cp = sub.add_parser("compare",
                        help="Detector-based cross-run regression "
                             "gate over a run ledger")
    cp.add_argument("--ledger", required=True,
                    help="run ledger JSONL (runlog.RunLedger)")
    cp.add_argument("--kind", default=None,
                    help="only compare records of this kind")
    cp.add_argument("--label", default=None,
                    help="only compare records with this label")
    cp.add_argument("--warmup", type=int, default=4,
                    help="detector warmup samples (baseline prefix is "
                         "cycled to fill it; default 4)")
    cp.add_argument("--max-regressions", type=int, default=0,
                    help="tolerated named regressions (default 0)")
    cp.add_argument("--json", default=None, metavar="PATH",
                    help="write the full verdict JSON here")

    inc = sub.add_parser("incidents",
                         help="list captured incident bundles "
                              "(kind=incident ledger records) joined "
                              "with their replay/bisect verdicts "
                              "(kind=incident_replay)")
    inc.add_argument("--ledger", required=True,
                     help="run ledger JSONL (runlog.RunLedger)")
    inc.add_argument("--kind", default=None,
                     help="only incidents triggered by this flight "
                          "kind (e.g. train.nan_skip)")
    inc.add_argument("--json", default=None, metavar="PATH",
                     help="write the joined rows JSON here")

    im = sub.add_parser("import",
                        help="fold historical BENCH_r*.json artifacts "
                             "into a ledger as imported_bench records")
    im.add_argument("files", nargs="+", help="BENCH_r*.json paths")
    im.add_argument("--ledger", required=True,
                    help="run ledger JSONL to append into")

    a = ap.parse_args(argv)
    if a.cmd == "attribute":
        return _cmd_attribute(a)
    if a.cmd == "blame":
        return _cmd_blame(a)
    if a.cmd == "compare":
        return _cmd_compare(a)
    if a.cmd == "incidents":
        return _cmd_incidents(a)
    return _cmd_import(a)


if __name__ == "__main__":
    sys.exit(main())
