"""Optimizers (parity: python/paddle/optimizer/ + reference C++ kernels in
operators/optimizers/ — sgd_op, momentum_op, adam_op, adamw, lamb, lars,
adagrad, adadelta, rmsprop).

Design: paddle-style stateful API (``opt.step()`` reads ``param.grad``) over
pure functional update rules.  Each optimizer exposes ``update(param, grad,
state, lr) -> (new_param, new_state)`` as pure jax code so paddle_tpu.jit can
fuse the whole update into the training step, and ``step()`` applies it
eagerly for dygraph parity.
"""
from paddle_tpu.optimizer.optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, LarsMomentum, Adam, AdamW, Adamax, Adagrad,
    Adadelta, RMSProp, Lamb)
from paddle_tpu.optimizer import lr  # noqa: F401
