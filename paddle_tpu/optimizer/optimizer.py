"""Optimizer implementations. See package docstring for design."""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Parameter, Tensor, no_grad

__all__ = ["Optimizer", "SGD", "Momentum", "LarsMomentum", "Adam", "AdamW",
           "Adamax", "Adagrad", "Adadelta", "RMSProp", "Lamb"]


def _dense_grad(g):
    """Optimizer paths that only know dense math densify SelectedRows
    grads up front (base Optimizer.step keeps them sparse)."""
    from paddle_tpu.framework.selected_rows import SelectedRows
    if isinstance(g, SelectedRows):
        return Tensor(g.merge().to_dense())
    return g


def _as_float(v):
    if isinstance(v, Tensor):
        return v._data
    return v


class Optimizer:
    """Base (reference: python/paddle/optimizer/optimizer.py Optimizer)."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        from paddle_tpu.optimizer.lr import LRScheduler
        self._lr = learning_rate
        self._lr_scheduler = learning_rate if isinstance(
            learning_rate, LRScheduler) else None
        if parameters is not None:
            self._parameter_list = list(parameters)
        else:
            self._parameter_list = None
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators: Dict[int, dict] = {}
        self._global_step = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        return float(self._lr)

    def set_lr(self, value):
        if self._lr_scheduler is not None:
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._lr = value

    @property
    def _param_groups(self):
        return self._parameter_list

    # -- state ---------------------------------------------------------------
    def _state_for(self, p: Parameter) -> dict:
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self.init_state(p._data)
            self._accumulators[key]["__param_ref"] = p
        return self._accumulators[key]

    def init_state(self, value) -> dict:
        return {}

    def update(self, param, grad, state: dict, lr):
        """Pure update rule: (array, array, state-dict of arrays, lr) →
        (new_param, new_state).  Override in subclasses."""
        raise NotImplementedError

    def _apply_decay(self, p, param, grad):
        """Coupled decay folded into the gradient (reference:
        append_regularization_ops + L1/L2DecayRegularizer).  Per-parameter
        ParamAttr regularizers take precedence over the optimizer-level
        weight_decay, matching the reference's behavior; AdamW overrides to
        decouple."""
        reg = getattr(p, "regularizer", None)
        if reg is not None:
            return grad + reg(param)
        wd = self._weight_decay
        if wd is None:
            return grad
        if callable(wd) and not isinstance(wd, (int, float)):
            return grad + wd(param)  # L1Decay/L2Decay instance
        return grad + float(wd) * param

    @no_grad()
    def step(self):
        from paddle_tpu.framework.selected_rows import SelectedRows
        lr = self.get_lr()
        params = self._parameter_list
        if params is None:
            raise ValueError("Optimizer created without parameters")
        grads_and_params = [(p, p._grad) for p in params
                            if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            # clip operates on dense tensors; densify SelectedRows first
            # (the reference likewise excludes sparse grads from global
            # clipping or merges them — clip_op on SelectedRows densifies)
            clipped = self._grad_clip(
                [(p, Tensor(g.to_dense()) if isinstance(g, SelectedRows)
                  else g) for p, g in grads_and_params])
            grads_and_params = clipped
        self._global_step += 1
        for p, g in grads_and_params:
            state = self._state_for(p)
            p_lr = lr * getattr(p, "optimize_attr",
                                {"learning_rate": 1.0})["learning_rate"]
            if isinstance(g, SelectedRows):
                sr = g.merge()        # MergeAdd: duplicate ids accumulate
                if hasattr(self, "update_sparse"):
                    # row-sparse fast path (sgd_op.h / adam_op.h lazy_mode
                    # SelectedRows branches); weight decay skipped like
                    # the reference's sparse regularization behaviour
                    new_p, new_state = self.update_sparse(
                        p._data, sr, state, p_lr)
                    p._data = new_p
                    state.update(new_state)
                    continue
                garr = sr.to_dense()
            else:
                garr = g._data if isinstance(g, Tensor) else g
            garr = self._apply_decay(p, p._data, garr)
            new_p, new_state = self.update(p._data, garr, state, p_lr)
            p._data = new_p
            state.update(new_state)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- checkpoint ----------------------------------------------------------
    def state_dict(self) -> dict:
        sd = {}
        for i, p in enumerate(self._parameter_list or []):
            st = self._accumulators.get(id(p))
            if st:
                for k, v in st.items():
                    if k == "__param_ref":
                        continue
                    sd[f"{p.name}_{k}"] = Tensor(v) if not isinstance(
                        v, (int, float)) else v
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"] = self._lr_scheduler.state_dict()
        sd["@global_step"] = self._global_step
        return sd

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get("@global_step", 0))
        if self._lr_scheduler is not None and "LR_Scheduler" in state_dict:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])
        for p in self._parameter_list or []:
            st = self._state_for(p)
            for k in list(st.keys()):
                if k == "__param_ref":
                    continue
                key = f"{p.name}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    st[k] = v._data if isinstance(v, Tensor) else v

    # -- functional bridge for jit/distributed ------------------------------
    def functional_update(self, params: dict, grads: dict, states: dict,
                          lr=None, step=None):
        """Pure pytree update used by paddle_tpu.jit.TrainStep and the Fleet
        strategies: no host state is touched."""
        lr = self.get_lr() if lr is None else lr
        new_params, new_states = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_states[name] = states.get(name, {})
                continue
            st = dict(states.get(name, {}))
            if self._weight_decay is not None and not isinstance(
                    self, AdamW):
                wd = self._weight_decay
                g = g + (wd(p) if callable(wd) else float(wd) * p)
            np_, ns = self.update(p, g, st, lr)
            new_params[name] = np_
            new_states[name] = ns
        return new_params, new_states

    def functional_init_states(self, params: dict) -> dict:
        return {name: {k: v for k, v in self.init_state(p).items()}
                for name, p in params.items()}


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op."""

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)

    def update(self, param, grad, state, lr):
        return param - lr * grad, {}

    def update_sparse(self, param, sr, state, lr):
        """sgd_op.h SelectedRows branch: touch only the gradient rows."""
        return param.at[sr.rows].add(-lr * sr.values.astype(param.dtype)), {}


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op (use_nesterov attr)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, value):
        return {"velocity": jnp.zeros_like(value)}

    def update(self, param, grad, state, lr):
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            new_p = param - lr * (grad + self._momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {"velocity": v}


class LarsMomentum(Optimizer):
    """Layer-wise adaptive rate scaling + momentum (reference:
    operators/optimizers/lars_momentum_op.cc; fleet meta-optimizer
    fleet/meta_optimizers/lars_optimizer.py swaps it in for large-batch
    training).

    local_lr = lr * coeff * ||p|| / (||g|| + wd * ||p|| + eps)
    v' = mu * v + local_lr * (g + wd * p);  p' = p - v'
    """

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, epsilon=1e-9, parameters=None,
                 exclude_from_weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def init_state(self, value):
        return {"velocity": jnp.zeros_like(value)}

    def _wd_for(self, name: str) -> float:
        if name and any(s in name for s in self._exclude):
            return 0.0
        return self._lars_weight_decay

    def update(self, param, grad, state, lr, wd=None):
        wd = self._lars_weight_decay if wd is None else wd
        p_norm = jnp.sqrt(jnp.sum(param.astype(jnp.float32) ** 2))
        g_norm = jnp.sqrt(jnp.sum(grad.astype(jnp.float32) ** 2))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * p_norm /
            (g_norm + wd * p_norm + self._epsilon),
            lr).astype(param.dtype)
        v = self._momentum * state["velocity"] + local_lr * (
            grad + wd * param)
        return param - v, {"velocity": v}

    @no_grad()
    def step(self):
        # override: route the per-param name through to honor
        # exclude_from_weight_decay (reference lars_momentum_op honors it)
        lr = self.get_lr()
        params = self._parameter_list
        if params is None:
            raise ValueError("Optimizer created without parameters")
        grads_and_params = [(p, _dense_grad(p._grad)) for p in params
                            if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            grads_and_params = self._grad_clip(
                [(p, g) for p, g in grads_and_params])
        self._global_step += 1
        for p, g in grads_and_params:
            state = self._state_for(p)
            p_lr = lr * getattr(p, "optimize_attr",
                                {"learning_rate": 1.0})["learning_rate"]
            garr = g._data if isinstance(g, Tensor) else g
            new_p, new_state = self.update(p._data, garr, state, p_lr,
                                           wd=self._wd_for(p.name))
            p._data = new_p
            state.update(new_state)

    def functional_update(self, params: dict, grads: dict, states: dict,
                          lr=None, step=None):
        lr = self.get_lr() if lr is None else lr
        new_params, new_states = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_states[name] = states.get(name, {})
                continue
            np_, ns = self.update(p, g, dict(states.get(name, {})), lr,
                                  wd=self._wd_for(name))
            new_params[name] = np_
            new_states[name] = ns
        return new_params, new_states


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op (beta pow accumulators)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_fused=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode
        self._use_fused = use_fused

    def init_state(self, value):
        return {"moment1": jnp.zeros_like(value),
                "moment2": jnp.zeros_like(value),
                "beta1_pow": jnp.ones((), value.dtype if jnp.issubdtype(
                    value.dtype, jnp.floating) else jnp.float32),
                "beta2_pow": jnp.ones((), value.dtype if jnp.issubdtype(
                    value.dtype, jnp.floating) else jnp.float32)}

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        if self._use_fused:
            from paddle_tpu.ops.pallas import fused_adam
            if fused_adam.supported():
                new_p, m, v = fused_adam.fused_adam_update(
                    param, grad, state["moment1"], state["moment2"],
                    lr_t=lr_t, beta1=b1, beta2=b2, eps=eps,
                    wd_lr=self._fused_wd_lr(lr))
                return new_p, {"moment1": m, "moment2": v,
                               "beta1_pow": b1p, "beta2_pow": b2p}
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * grad * grad
        new_p = param - lr_t * m / (jnp.sqrt(v) + eps)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}

    def _fused_wd_lr(self, lr):
        return 0.0  # Adam's L2 decay arrives inside the grad (regularizer)

    def _fused_active(self):
        if not self._use_fused:
            return False
        from paddle_tpu.ops.pallas import fused_adam
        return fused_adam.supported()

    def update_sparse(self, param, sr, state, lr):
        """adam_op.h lazy_mode SelectedRows branch: moments and param move
        only on the gradient's rows (non-lazy semantics would decay every
        row's moments; the reference defaults sparse Adam to lazy in
        dygraph for exactly this cost reason).  Falls back to the dense
        rule when lazy_mode=False."""
        if not self._lazy_mode:
            g = sr.to_dense()
            return self.update(param, g, state, lr)
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        rows, vals = sr.rows, sr.values.astype(param.dtype)
        m_r = b1 * state["moment1"][rows] + (1 - b1) * vals
        v_r = b2 * state["moment2"][rows] + (1 - b2) * vals * vals
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        new_p = param.at[rows].add(-lr_t * m_r / (jnp.sqrt(v_r) + eps))
        return new_p, {
            "moment1": state["moment1"].at[rows].set(m_r),
            "moment2": state["moment2"].at[rows].set(v_r),
            "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py —
    decay applied directly to param, not through the moment estimates)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, use_fused=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, use_fused=use_fused)
        self._coeff = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_decay(self, p, param, grad):
        return grad  # decoupled — handled in update via param name check

    def _fused_wd_lr(self, lr):
        return lr * float(self._coeff)   # decoupled decay inside the kernel

    def update(self, param, grad, state, lr):
        new_p, new_state = super().update(param, grad, state, lr)
        if self._fused_active():
            return new_p, new_state      # decay already applied in-kernel
        decay = lr * float(self._coeff)
        new_p = new_p - decay * param
        return new_p, new_state

    def step(self):
        if self._apply_decay_param_fun is None:
            return super().step()
        # selectively decay
        coeff = self._coeff
        lr = self.get_lr()
        self._global_step += 1
        grads_and_params = [(p, _dense_grad(p._grad))
                            for p in self._parameter_list
                            if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            grads_and_params = self._grad_clip(grads_and_params)
        for p, g in grads_and_params:
            state = self._state_for(p)
            garr = g._data if isinstance(g, Tensor) else g
            b1, b2, eps = self._beta1, self._beta2, self._epsilon
            m = b1 * state["moment1"] + (1 - b1) * garr
            v = b2 * state["moment2"] + (1 - b2) * garr * garr
            b1p = state["beta1_pow"] * b1
            b2p = state["beta2_pow"] * b2
            lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
            new_p = p._data - lr_t * m / (jnp.sqrt(v) + eps)
            if self._apply_decay_param_fun(p.name):
                new_p = new_p - lr * coeff * p._data
            p._data = new_p
            state.update({"moment1": m, "moment2": v, "beta1_pow": b1p,
                          "beta2_pow": b2p})


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, value):
        return {"moment": jnp.zeros_like(value),
                "inf_norm": jnp.zeros_like(value),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment"] + (1 - b1) * grad
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(grad))
        b1p = state["beta1_pow"] * b1
        new_p = param - (lr / (1 - b1p)) * m / (u + eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, value):
        return {"moment": jnp.full_like(value, self._init_acc)}

    def update(self, param, grad, state, lr):
        acc = state["moment"] + grad * grad
        new_p = param - lr * grad / (jnp.sqrt(acc) + self._epsilon)
        return new_p, {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon, self._rho = epsilon, rho

    def init_state(self, value):
        return {"avg_squared_grad": jnp.zeros_like(value),
                "avg_squared_update": jnp.zeros_like(value)}

    def update(self, param, grad, state, lr):
        rho, eps = self._rho, self._epsilon
        asg = rho * state["avg_squared_grad"] + (1 - rho) * grad * grad
        upd = grad * jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        return param - lr * upd, {"avg_squared_grad": asg,
                                  "avg_squared_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_state(self, value):
        st = {"mean_square": jnp.zeros_like(value),
              "momentum": jnp.zeros_like(value)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(value)
        return st

    def update(self, param, grad, state, lr):
        rho, eps = self._rho, self._epsilon
        ms = rho * state["mean_square"] + (1 - rho) * grad * grad
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * grad
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state["momentum"] + lr * grad / denom
        new_p = param - mom
        st = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            st["mean_grad"] = mg
        return new_p, st


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op — layer-wise trust ratio."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, value):
        return {"moment1": jnp.zeros_like(value),
                "moment2": jnp.zeros_like(value),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def update(self, param, grad, state, lr, decay=True):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment1"] + (1 - b1) * grad
        v = b2 * state["moment2"] + (1 - b2) * grad * grad
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps)
        if decay:
            r = r + self._lamb_wd * param
        w_norm = jnp.linalg.norm(param.reshape(-1))
        r_norm = jnp.linalg.norm(r.reshape(-1))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = param - lr * trust * r
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}

    def step(self):
        if self._exclude_fn is None:
            return super().step()
        lr = self.get_lr()
        self._global_step += 1
        grads_and_params = [(p, _dense_grad(p._grad))
                            for p in self._parameter_list
                            if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            grads_and_params = self._grad_clip(grads_and_params)
        for p, g in grads_and_params:
            state = self._state_for(p)
            garr = g._data if isinstance(g, Tensor) else g
            decay = not self._exclude_fn(p)
            new_p, new_state = self.update(p._data, garr, state, lr,
                                           decay=decay)
            p._data = new_p
            state.update(new_state)
