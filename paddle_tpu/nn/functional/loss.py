"""Loss functionals (parity: python/paddle/nn/functional/loss.py; reference
kernels: operators/cross_entropy_op.*, softmax_with_cross_entropy_op.*,
bce_loss_op.*, smooth_l1_loss_op.*, kldiv_loss_op.*, margin_rank_loss_op.*).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "l1_loss", "mse_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "ctc_loss", "square_error_cost", "log_loss",
    "sigmoid_focal_loss", "dice_loss", "npair_loss", "triplet_margin_loss",
    "soft_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, name=None):
    def _ce(logits, lab, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label:
            loss = -jnp.sum(lab * logp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            loss = -jnp.take_along_axis(
                logp, jnp.expand_dims(jnp.maximum(lab_i, 0), axis), axis=axis)
            loss = jnp.squeeze(loss, axis=axis)
            valid = (lab_i != ignore_index)
            loss = jnp.where(valid, loss, 0.0)
            if w:
                wt = jnp.take(w[0], jnp.maximum(lab_i, 0), axis=0)
                wt = jnp.where(valid, wt, 0.0)
                loss = loss * wt
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label]
    nondiff = (1,) if not soft_label else ()
    if weight is not None:
        args.append(weight)
    return apply1(_ce, *args, nondiff=nondiff, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    if loss.ndim == logits.ndim - 1:
        from paddle_tpu.tensor.manipulation import unsqueeze
        loss = unsqueeze(loss, axis)
    if return_softmax:
        from paddle_tpu.nn.functional.activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def _bce(p, l, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(l * jnp.log(p) + (1 - l) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply1(_bce, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def _bcel(z, l, *extra):
        i = 0
        if pos_weight is not None:
            pw = extra[i]; i += 1
            l_w = 1.0 + (pw - 1.0) * l
            base = (1.0 - l) * z + l_w * (
                jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0.0))
        else:
            base = jnp.maximum(z, 0.0) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if weight is not None:
            base = base * extra[i]
        return _reduce(base, reduction)
    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply1(_bcel, *args, name="bce_with_logits")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def _nll(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, jnp.expand_dims(
            jnp.maximum(lab_i, 0), 1), axis=1)[:, 0]
        valid = lab_i != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.maximum(lab_i, 0))
            wt = jnp.where(valid, wt, 0.0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply1(_nll, *args, nondiff=(1,), name="nll_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply1(lambda a, b: _reduce(jnp.abs(a - b), reduction), input,
                  label, name="l1_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply1(lambda a, b: _reduce((a - b) ** 2, reduction), input, label,
                  name="mse_loss")


def square_error_cost(input, label, name=None):
    return apply1(lambda a, b: (a - b) ** 2, input, label,
                  name="square_error_cost")


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply1(
        lambda p, l: -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(
            1 - p + epsilon), input, label, name="log_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _sl1(a, b):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d,
                         delta * (abs_d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply1(_sl1, input, label, name="smooth_l1_loss")


def kl_div(input, label, reduction="mean", name=None):
    def _kl(logp, t):
        loss = t * (jnp.log(jnp.maximum(t, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply1(_kl, input, label, name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def _mrl(a, b, l):
        loss = jnp.maximum(0.0, -l * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply1(_mrl, input, other, label, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def _hel(a, l):
        loss = jnp.where(l == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply1(_hel, input, label, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def _cel(a, b, l):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(l == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply1(_cel, input1, input2, label, name="cosine_embedding_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, l, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * l + (1 - p) * (1 - l)
        a_t = alpha * l + (1 - alpha) * (1 - l)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = [logit, label]
    if normalizer is not None:
        args.append(normalizer)
    return apply1(_sfl, *args, name="sigmoid_focal_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    def _dice(p, l):
        l_oh = jax.nn.one_hot(l[..., 0].astype(jnp.int32), p.shape[-1])
        reduce_dims = tuple(range(1, p.ndim))
        inter = 2.0 * jnp.sum(p * l_oh, axis=reduce_dims)
        denom = jnp.sum(p, axis=reduce_dims) + jnp.sum(l_oh, axis=reduce_dims)
        return jnp.mean(1.0 - (inter + epsilon) / (denom + epsilon))
    return apply1(_dice, input, label, nondiff=(1,), name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    def _np(a, p, l):
        sim = jnp.matmul(a, p.T)
        lab = l.reshape(-1)
        tgt = (lab[:, None] == lab[None, :]).astype(sim.dtype)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) +
                        jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg
    return apply1(_np, anchor, positive, labels, nondiff=(2,),
                  name="npair_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return apply1(_tml, input, positive, negative, name="triplet_margin_loss")


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply1(lambda a, l: _reduce(jnp.log1p(jnp.exp(-l * a)), reduction),
                  input, label, name="soft_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (reference: operators/warpctc_op → here a lax.scan DP, no warpctc).

    log_probs: (T, N, C) logits (will be log-softmaxed), labels: (N, S) padded.
    """
    def _ctc(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp, axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        # extended label sequence with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_len = 2 * lab_len.astype(jnp.int32) + 1
        NEG = -1e30
        # alpha init
        alpha0 = jnp.full((N, 2 * S + 1), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(N), blank])
        first_lab = jnp.where(lab_len > 0, ext[:, 1], blank)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, lp[0, jnp.arange(N), first_lab], NEG))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), dtype=bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate(
                [jnp.full((N, 1), NEG), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((N, 2), NEG), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, NEG, a_shift2)
            merged = jnp.logaddexp(alpha, a_shift1)
            merged = jnp.logaddexp(merged, a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            # freeze past input length
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
        idx_last = jnp.maximum(ext_len - 1, 0)
        idx_prev = jnp.maximum(ext_len - 2, 0)
        ll = jnp.logaddexp(
            jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0],
            jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0])
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return apply1(_ctc, log_probs, labels, input_lengths, label_lengths,
                  nondiff=(1, 2, 3), name="ctc_loss")
