"""Convolution functionals (reference kernels: operators/conv_op.*,
conv_transpose_op.*, operators/math/im2col — here: lax.conv_general_dilated,
which XLA tiles straight onto the MXU; no im2col materialisation).

Layout: accepts paddle's NCHW/NHWC ``data_format``; weights OIHW (paddle
convention).  On TPU, NHWC + bf16 is the fast path — layers expose
``data_format`` so models can run either.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuplify(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n, strides=None):
    """paddle padding: int | list[int] (per-dim) | list of pairs | 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer))
                                 for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                for i in range(n)]
    return [tuple(int(q) for q in p) for p in padding]


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last, name):
    strides = _tuplify(stride, n)
    dil = _tuplify(dilation, n)
    pad = _norm_padding(padding, n)
    if channel_last:
        spatial = "".join("DHW"[3 - n:][i] for i in range(n))
        lhs_spec = "N" + spatial + "C"
    else:
        spatial = "".join("DHW"[3 - n:][i] for i in range(n))
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, out_spec))

    def _conv(a, w, *maybe_b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            c_axis = out.ndim - 1 if channel_last else 1
            shape[c_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply1(_conv, x, weight, bias, name=name)
    return apply1(_conv, x, weight, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    data_format in ("NLC",), "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    data_format == "NHWC", "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    data_format == "NDHWC", "conv3d")


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, n, channel_last, output_size, name):
    strides = _tuplify(stride, n)
    dil = _tuplify(dilation, n)
    pad = _norm_padding(padding, n)
    out_pad = _tuplify(output_padding, n) if output_padding is not None else (0,) * n
    spatial = "".join("DHW"[3 - n:][i] for i in range(n))
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle transpose-conv weight layout: (in_channels, out_channels/groups,
    # *k).  For groups>1 we reshape to OI-per-group so feature_group_count
    # sees rhs I = in_channels/groups with output blocks contiguous.
    rhs_spec = ("IO" if groups == 1 else "OI") + spatial
    w_shape = tuple(weight.shape)
    if groups > 1:
        cin, cog = w_shape[0], w_shape[1]
        w_shape = (cog * groups, cin // groups) + w_shape[2:]
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), w_shape, (lhs_spec, rhs_spec, lhs_spec))

    pad_pairs = pad

    def _convt(a, w, *maybe_b):
        # transpose conv = conv with lhs dilation + spatially flipped kernel
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            cin, cog = w.shape[0], w.shape[1]
            # (g, cin/g, cog, *k) → (g, cog, cin/g, *k) → (cout, cin/g, *k)
            wg = w.reshape((groups, cin // groups, cog) + w.shape[2:])
            wg = jnp.swapaxes(wg, 1, 2)
            w = wg.reshape((groups * cog, cin // groups) + w.shape[2:])
        k_shape = w.shape[2:]
        if isinstance(pad_pairs, str):
            trans_pad = pad_pairs
        else:
            trans_pad = []
            for i in range(n):
                k_eff = (k_shape[i] - 1) * dil[i] + 1
                lo = k_eff - 1 - pad_pairs[i][0]
                hi = k_eff - 1 - pad_pairs[i][1] + out_pad[i]
                trans_pad.append((lo, hi))
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=(1,) * n, padding=trans_pad,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        if maybe_b:
            b = maybe_b[0]
            shape = [1] * out.ndim
            c_axis = out.ndim - 1 if channel_last else 1
            shape[c_axis] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply1(_convt, x, weight, bias, name=name)
    return apply1(_convt, x, weight, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1, data_format == "NLC",
                              output_size, "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 2, data_format == "NHWC",
                              output_size, "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3, data_format == "NDHWC",
                              output_size, "conv3d_transpose")
