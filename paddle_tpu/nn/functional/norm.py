"""Normalization functionals (reference kernels: operators/batch_norm_op.*,
layer_norm_op.*, instance_norm_op.*, group_norm_op.*, norm_op.*)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply, apply1

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "normalize"]


def _mean_var_1pass(a, axes, keepdims=False):
    """mean and variance as SIBLING reductions over one input read.

    ``jnp.var`` reduces twice sequentially (mean, then mean((x-m)^2)) —
    the second pass depends on the first, so XLA cannot fuse them and the
    activation is read twice (3x with the normalize).  E[x^2]-E[x]^2 puts
    both accumulators in one multi-output reduction fusion: profiled on
    one chip, ResNet-50's step time is dominated by exactly these
    BN-stat passes, not the convs.  Accumulation in f32 keeps bf16
    activations numerically safe.

    Numerics (advisor r3: E[x^2]-E[x]^2 cancels when |mean| >> std):
    - low-precision inputs (bf16/f16, the AMP hot path) keep the one-pass
      form — any cancellation error in the f32 accumulators is below the
      input's own quantization (bf16 ULP at |x| dominates), so the clamp
      is a true no-op there.  Shift-K variants were measured and
      rejected: a slice-K costs ResNet-50 ~16% and a running-mean-K
      ~40% (both break XLA's multi-output stat-fusion shape).
    - float inputs that CAN carry sub-cancellation variance (f32/f64)
      take the exact two-pass form instead — the reference's semantics,
      at the cost of the second activation read.
    """
    af = a.astype(jnp.float32)
    if any(a.shape[ax] == 0 for ax in axes):
        # empty reduction: the stats are NaN either way; keep it finite
        m = jnp.mean(af, axis=axes, keepdims=keepdims)
        v = jnp.zeros_like(m)
        return m.astype(a.dtype), v.astype(a.dtype)
    if a.dtype in (jnp.float32, jnp.float64):
        m = jnp.mean(af, axis=axes, keepdims=True)
        v = jnp.mean(jnp.square(af - m), axis=axes, keepdims=True)
    else:
        m = jnp.mean(af, axis=axes, keepdims=True)
        msq = jnp.mean(af * af, axis=axes, keepdims=True)
        v = jnp.maximum(msq - m * m, 0.0)
    if not keepdims:
        m = jnp.squeeze(m, axis=axes)
        v = jnp.squeeze(v, axis=axes)
    return m.astype(a.dtype), v.astype(a.dtype)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Functional batch norm.

    Running-stat update happens host-side on the Tensor buffers (matching the
    reference's in-place mean/var outputs, operators/batch_norm_op.cc); under
    jit capture use Layer form which threads stats functionally.
    """
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    use_batch_stats = training and not use_global_stats

    def _stats_axes(a):
        if channel_last:
            return tuple(range(a.ndim - 1))
        return (0,) + tuple(range(2, a.ndim))

    def _bn(a, mean, var, *wb):
        axes = _stats_axes(a)
        shape = [1] * a.ndim
        c_axis = a.ndim - 1 if channel_last else (1 if a.ndim > 1 else 0)
        shape[c_axis] = a.shape[c_axis]
        if use_batch_stats:
            m, v = _mean_var_1pass(a, axes)
        else:
            m, v = mean, var
        out = (a - m.reshape(shape)) * jax.lax.rsqrt(
            v.reshape(shape) + epsilon)
        if wb:
            w = wb[0]
            out = out * w.reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out

    args = [x, running_mean, running_var]
    nondiff = (1, 2)
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    out = apply1(_bn, *args, nondiff=nondiff, name="batch_norm")

    # Running-stat update: works eagerly AND under jit capture — the buffer's
    # ._data becomes a tracer which paddle_tpu.jit harvests as a functional
    # output (see StaticFunction/TrainStep buffer threading).
    if use_batch_stats and isinstance(running_mean, Tensor):
        axes = _stats_axes(x._data)
        m, v = _mean_var_1pass(x._data, axes)
        n = 1
        for ax in axes:
            n *= x._data.shape[ax]
        unbiased = v * (n / max(n - 1, 1))
        running_mean._data = momentum * running_mean._data + (1 - momentum) * m
        running_var._data = momentum * running_var._data + (1 - momentum) * unbiased
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(list(normalized_shape))

    def _ln(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        m, v = _mean_var_1pass(a, axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + epsilon)
        if wb:
            out = out * wb[0]
            if len(wb) > 1:
                out = out + wb[1]
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply1(_ln, *args, name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def _in(a, *wb):
        axes = tuple(range(2, a.ndim))
        m, v = _mean_var_1pass(a, axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        if wb:
            shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply1(_in, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NDHWC", "NLC")

    def _gn(a, *wb):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = num_groups
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        m, v = _mean_var_1pass(grouped, axes, keepdims=True)
        out = ((grouped - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_t.shape)
        if wb:
            shape = [1, c] + [1] * (a_t.ndim - 2)
            out = out * wb[0].reshape(shape)
            if len(wb) > 1:
                out = out + wb[1].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return apply1(_gn, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(a):
        sq = a * a
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[c_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pads)
        dims = [1] * a.ndim
        dims[c_axis] = size
        summed = jax.lax.reduce_window(padded, 0.0, jax.lax.add, tuple(dims),
                                       (1,) * a.ndim, "VALID")
        return a / jnp.power(k + alpha * summed, beta)
    return apply1(_lrn, x, name="local_response_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _normalize(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply1(_normalize, x, name="normalize")
