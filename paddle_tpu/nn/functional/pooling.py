"""Pooling functionals (reference kernels: operators/pool_op.*,
operators/math/pooling.*) via lax.reduce_window."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import apply1

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
           "adaptive_max_pool2d", "adaptive_max_pool3d", "max_unpool2d"]


def _tuplify(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _norm_pad(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding),) * 2] * n
    padding = list(padding)
    if all(isinstance(p, (int, np.integer)) for p in padding):
        if len(padding) == n:
            return [(int(p), int(p)) for p in padding]
        if len(padding) == 2 * n:
            return [(int(padding[2 * i]), int(padding[2 * i + 1]))
                    for i in range(n)]
    return [tuple(int(q) for q in p) for p in padding]


def _ceil_extra_pad(size, p0, p1, k, s):
    """Extra high-side padding for ceil_mode, with the reference's clamp:
    the last window must start inside input+left-padding (pooling.cc
    AdjustPoolSize semantics — torch/paddle agree)."""
    span = size + p0 + p1 - k
    n_floor = span // s + 1
    n_ceil = -(-span // s) + 1
    if n_ceil > n_floor and (n_ceil - 1) * s < size + p0:
        return (n_ceil - 1) * s + k - (size + p0 + p1)
    return 0


def _pool(x, kernel, stride, padding, n, channel_last, mode, ceil_mode,
          exclusive, name):
    k = _tuplify(kernel, n)
    s = _tuplify(stride if stride is not None else kernel, n)
    base_pad = _norm_pad(padding, n)

    def _run(a):
        nd = a.ndim
        pad = base_pad
        if ceil_mode and not isinstance(pad, str):
            spatial = a.shape[1:-1] if channel_last else a.shape[2:]
            # build a fresh list — _run may re-execute (e.g. under remat)
            # and must not accumulate onto the closed-over padding
            pad = [(p0, p1 + _ceil_extra_pad(size, p0, p1, k[i], s[i]))
                   for i, (size, (p0, p1)) in enumerate(zip(spatial, pad))]
        if channel_last:
            dims = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            pads = [(0, 0)] + (list(pad) if not isinstance(pad, str) else pad) + [(0, 0)] \
                if not isinstance(pad, str) else pad
        else:
            dims = (1, 1) + k
            strides = (1, 1) + s
            pads = [(0, 0), (0, 0)] + list(pad) if not isinstance(pad, str) else pad
        if isinstance(pad, str):
            pads = pad
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, dims, strides,
                                         pads)
        # avg
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides,
                                       pads)
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                           strides, pads)
            return summed / counts
        return summed / float(np.prod(k))
    return apply1(_run, x, name=name)


def _max_pool2d_with_mask(x, kernel, stride, padding, name,
                          ceil_mode=False):
    """Max pool that also returns the argmax as flattened H*W input
    indices (reference: operators/pool_with_index_op — the mask consumed
    by max_unpool2d).  NCHW only; windows are materialised as kh*kw
    strided slices, so this stays a static-shape gather/argmax XLA
    likes."""
    kh, kw = _tuplify(kernel, 2)
    sh, sw = _tuplify(stride if stride is not None else kernel, 2)
    pad = _norm_pad(padding, 2)
    if isinstance(pad, str):
        raise ValueError("return_mask needs explicit int padding")
    (pt, pb), (pl, pr) = pad

    def _n_out(size, p0, p1, k, s):
        span = size + p0 + p1 - k
        n = (-(-span // s) if ceil_mode else span // s) + 1
        # ceil-mode clamp: last window must start inside input+left pad
        if ceil_mode and n > span // s + 1 and (n - 1) * s >= size + p0:
            n -= 1
        return n

    def _run(a):
        N, C, H, W = a.shape
        oh = _n_out(H, pt, pb, kh, sh)
        ow = _n_out(W, pl, pr, kw, sw)
        # ceil mode may need the bottom/right padding widened so every
        # window has backing data (-inf filled, never the argmax)
        pb_e = max(pb, (oh - 1) * sh + kh - H - pt)
        pr_e = max(pr, (ow - 1) * sw + kw - W - pl)
        padded = jnp.pad(a, [(0, 0), (0, 0), (pt, pb_e), (pl, pr_e)],
                         constant_values=-jnp.inf)
        wins, gidx = [], []
        for i in range(kh):
            for j in range(kw):
                wins.append(padded[:, :, i:i + sh * oh:sh,
                                   j:j + sw * ow:sw])
                gy = jnp.arange(oh) * sh + i - pt
                gx = jnp.arange(ow) * sw + j - pl
                gidx.append(gy[:, None] * W + gx[None, :])
        stack = jnp.stack(wins)                      # [k, N, C, oh, ow]
        arg = jnp.argmax(stack, axis=0)              # [N, C, oh, ow]
        out = jnp.max(stack, axis=0)
        g = jnp.stack(gidx)                          # [k, oh, ow]
        flat_idx = g[arg,                            # window idx -> H*W idx
                     jnp.arange(oh).reshape(1, 1, oh, 1),
                     jnp.arange(ow).reshape(1, 1, 1, ow)]
        return out, flat_idx.astype(jnp.int32)
    from paddle_tpu.core import apply
    out, mask = apply(_run, x, name=name)
    mask.stop_gradient = True
    return out, mask


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        from paddle_tpu.tensor.manipulation import squeeze, transpose, unsqueeze
        if data_format == "NLC":
            x = transpose(x, [0, 2, 1])
        k = _tuplify(kernel_size, 1) + (1,)
        s = _tuplify(stride if stride is not None else kernel_size, 1) + (1,)
        p = _tuplify(padding, 1) + (0,)
        out, mask = _max_pool2d_with_mask(unsqueeze(x, -1), k, s, list(p),
                                          "max_pool1d",
                                          ceil_mode=ceil_mode)
        out, mask = squeeze(out, -1), squeeze(mask, -1)
        if data_format == "NLC":
            out = transpose(out, [0, 2, 1])
            mask = transpose(mask, [0, 2, 1])
        return out, mask
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "max", ceil_mode, True, "max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("return_mask supports NCHW")
        return _max_pool2d_with_mask(x, kernel_size, stride, padding,
                                     "max_pool2d", ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "max", ceil_mode, True, "max_pool2d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference: operators/unpool_op — scatter pooled values back to the
    positions the mask recorded."""
    kh, kw = _tuplify(kernel_size, 2)
    sh, sw = _tuplify(stride if stride is not None else kernel_size, 2)
    ph, pw = _tuplify(padding, 2)
    from paddle_tpu.core import apply1

    def _run(a, idx):
        N, C, oh, ow = a.shape
        if output_size is not None:
            H, W = [int(v) for v in output_size[-2:]]
        else:
            H = (oh - 1) * sh - 2 * ph + kh
            W = (ow - 1) * sw - 2 * pw + kw
        flat = jnp.zeros((N, C, H * W), a.dtype)
        out = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None],
            idx.reshape(N, C, -1)].set(a.reshape(N, C, -1))
        return out.reshape(N, C, H, W)
    return apply1(_run, x, indices, nondiff=(1,), name="max_unpool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        raise NotImplementedError(
            "max_pool3d(return_mask=True) is not implemented; "
            "use max_pool2d or file an issue")
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "max", ceil_mode, True, "max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC",
                 "avg", ceil_mode, exclusive, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 "avg", ceil_mode, exclusive, "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 "avg", ceil_mode, exclusive, "avg_pool3d")


def _adaptive_pool(x, output_size, n, mode, channel_last, name):
    if isinstance(output_size, (int, np.integer)):
        out_sizes = (int(output_size),) * n
    else:
        out_sizes = tuple(int(o) if o is not None else None
                          for o in output_size)

    def _run(a):
        spatial_start = 1 if channel_last else 2
        out = a
        for d in range(n):
            axis = spatial_start + d
            in_size = a.shape[axis]
            o = out_sizes[d] if out_sizes[d] is not None else in_size
            if in_size % o == 0:
                k = in_size // o
                new_shape = out.shape[:axis] + (o, k) + out.shape[axis + 1:]
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else \
                    jnp.mean(r, axis=axis + 1)
            else:
                # general adaptive: per-output-bin slices (static unrolled)
                slices = []
                for i in range(o):
                    lo = (i * in_size) // o
                    hi = ((i + 1) * in_size + o - 1) // o
                    sl = jax.lax.slice_in_dim(out, lo, hi, axis=axis)
                    red = jnp.max(sl, axis=axis, keepdims=True) \
                        if mode == "max" else jnp.mean(sl, axis=axis,
                                                       keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=axis)
        return out
    return apply1(_run, x, name=name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", False,
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        from paddle_tpu.tensor.manipulation import squeeze, unsqueeze
        L = int(x.shape[-1])
        o = output_size if isinstance(output_size, int) else output_size[0]
        if L % o:
            raise NotImplementedError(
                "adaptive_max_pool1d(return_mask=True) needs input length "
                "divisible by output_size (uniform windows)")
        out, mask = _max_pool2d_with_mask(unsqueeze(x, -1), (L // o, 1),
                                          (L // o, 1), [(0, 0), (0, 0)],
                                          "adaptive_max_pool1d")
        return squeeze(out, -1), squeeze(mask, -1)
    return _adaptive_pool(x, output_size, 1, "max", False,
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    if return_mask:
        H, W = int(x.shape[-2]), int(x.shape[-1])
        if isinstance(output_size, (list, tuple)):
            oh = H if output_size[0] is None else int(output_size[0])
            ow = W if output_size[1] is None else int(output_size[1])
        else:
            oh = ow = int(output_size)
        if H % oh or W % ow:
            raise NotImplementedError(
                "adaptive_max_pool2d(return_mask=True) needs input dims "
                "divisible by output_size (uniform windows)")
        return _max_pool2d_with_mask(x, (H // oh, W // ow),
                                     (H // oh, W // ow),
                                     [(0, 0), (0, 0)],
                                     "adaptive_max_pool2d")
    return _adaptive_pool(x, output_size, 2, "max", False,
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d(return_mask=True) is not implemented")
    return _adaptive_pool(x, output_size, 3, "max", False,
                          "adaptive_max_pool3d")
