"""Activation functionals (parity: python/paddle/nn/functional/activation.py;
reference kernels: paddle/fluid/operators/activation_op.*).

All map to jax.nn / jnp ops that XLA fuses into adjacent matmuls on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1

__all__ = [
    "relu", "relu_", "relu6", "elu", "selu", "celu", "gelu", "sigmoid",
    "hardsigmoid", "hardswish", "hardtanh", "hardshrink", "softshrink",
    "tanhshrink", "leaky_relu", "prelu", "rrelu", "log_sigmoid", "maxout",
    "silu", "swish", "mish", "softmax", "log_softmax", "softplus", "softsign",
    "tanh", "tanh_", "thresholded_relu", "glu", "gumbel_softmax",
]


def relu(x, name=None):
    return apply1(jax.nn.relu, x, name="relu")


def relu_(x, name=None):
    x._data = jax.nn.relu(x._data)
    return x


def relu6(x, name=None):
    return apply1(lambda a: jnp.clip(a, 0.0, 6.0), x, name="relu6")


def elu(x, alpha=1.0, name=None):
    return apply1(lambda a: jax.nn.elu(a, alpha=alpha), x, name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply1(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                  x, name="selu")


def celu(x, alpha=1.0, name=None):
    return apply1(lambda a: jax.nn.celu(a, alpha=alpha), x, name="celu")


def gelu(x, approximate=False, name=None):
    return apply1(lambda a: jax.nn.gelu(a, approximate=approximate), x,
                  name="gelu")


def sigmoid(x, name=None):
    return apply1(jax.nn.sigmoid, x, name="sigmoid")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply1(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), x,
                  name="hardsigmoid")


def hardswish(x, name=None):
    return apply1(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x,
                  name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply1(lambda a: jnp.clip(a, min, max), x, name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply1(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x,
                  name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply1(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        x, name="softshrink")


def tanhshrink(x, name=None):
    return apply1(lambda a: a - jnp.tanh(a), x, name="tanhshrink")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply1(lambda a: jax.nn.leaky_relu(a, negative_slope=negative_slope),
                  x, name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(a, w):
        if w.size == 1:
            wb = w.reshape(())
        elif data_format == "NCHW" and a.ndim > 1:
            wb = w.reshape((1, -1) + (1,) * (a.ndim - 2))
        else:
            wb = w
        return jnp.where(a > 0, a, wb * a)
    return apply1(_prelu, x, weight, name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        from paddle_tpu.tensor.random import default_generator
        k = default_generator.split()

        def _rrelu(a, key):
            slope = jax.random.uniform(key, a.shape, dtype=a.dtype,
                                       minval=lower, maxval=upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply1(_rrelu, x, k, name="rrelu")
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def log_sigmoid(x, name=None):
    return apply1(jax.nn.log_sigmoid, x, name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    def _maxout(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply1(_maxout, x, name="maxout")


def silu(x, name=None):
    return apply1(jax.nn.silu, x, name="silu")


def swish(x, name=None):
    return apply1(jax.nn.silu, x, name="swish")


def mish(x, name=None):
    return apply1(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x, name="mish")


def softmax(x, axis=-1, dtype=None, name=None):
    def _softmax(a):
        if dtype is not None:
            from paddle_tpu.core import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply1(_softmax, x, name="softmax")


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    def _lsm(a):
        if dtype is not None:
            from paddle_tpu.core import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply1(_lsm, x, name="log_softmax")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply1(
        lambda a: jnp.where(beta * a > threshold, a,
                            (1.0 / beta) * jnp.log1p(jnp.exp(beta * a))),
        x, name="softplus")


def softsign(x, name=None):
    return apply1(jax.nn.soft_sign, x, name="softsign")


def tanh(x, name=None):
    return apply1(jnp.tanh, x, name="tanh")


def tanh_(x, name=None):
    x._data = jnp.tanh(x._data)
    return x


def thresholded_relu(x, threshold=1.0, name=None):
    return apply1(lambda a: jnp.where(a > threshold, a, 0.0), x,
                  name="thresholded_relu")


def glu(x, axis=-1, name=None):
    def _glu(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply1(_glu, x, name="glu")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from paddle_tpu.tensor.random import default_generator
    k = default_generator.split()

    def _gs(a, key):
        g = jax.random.gumbel(key, a.shape, dtype=a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            # straight-through: value y_hard, gradient of the soft y
            # (parenthesized so the value term cancels exactly)
            y = y_hard + (y - jax.lax.stop_gradient(y))
        return y
    return apply1(_gs, x, k, name="gumbel_softmax")
