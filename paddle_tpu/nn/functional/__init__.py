"""paddle_tpu.nn.functional — parity with python/paddle/nn/functional/."""
from paddle_tpu.nn.functional.activation import *  # noqa: F401,F403
from paddle_tpu.nn.functional.attention import *  # noqa: F401,F403
from paddle_tpu.nn.functional.common import *  # noqa: F401,F403
from paddle_tpu.nn.functional.conv import *  # noqa: F401,F403
from paddle_tpu.nn.functional.loss import *  # noqa: F401,F403
from paddle_tpu.nn.functional.norm import *  # noqa: F401,F403
from paddle_tpu.nn.functional.pooling import *  # noqa: F401,F403

from paddle_tpu.tensor.manipulation import one_hot  # noqa: F401
from paddle_tpu.tensor.sequence import (  # noqa: F401
    embedding_bag, sequence_mask, sequence_pad, sequence_unpad,
    sequence_pool, sequence_softmax, sequence_reverse, segment_softmax,
    sequence_concat, sequence_enumerate, sequence_expand_as,
    sequence_first_step, sequence_last_step)
from paddle_tpu.nn.functional.extras import *  # noqa: F401,F403
