"""Common functionals: linear, dropout, embedding, pad, interpolate, etc.
(parity: python/paddle/nn/functional/common.py + input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1
from paddle_tpu.tensor.random import default_generator

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "pad", "zeropad2d", "interpolate",
           "upsample", "unfold", "fold", "bilinear", "cosine_similarity",
           "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
           "label_smooth", "class_center_sample", "pairwise_distance"]


def linear(x, weight, bias=None, name=None):
    """FC over the MXU. paddle weight layout: (in_features, out_features)."""
    if bias is not None:
        return apply1(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias,
                      name="linear")
    return apply1(jnp.matmul, x, weight, name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0:
        return x if isinstance(x, Tensor) else Tensor(x)
    if p == 1:
        return apply1(lambda a: jnp.zeros_like(a), x, name="dropout")
    k = default_generator.split()

    # the key rides as a runtime argument, NOT a closure cell: cells are
    # part of the dispatch-cache key, so a per-call key value would make
    # every dropout uncacheable (the round-4 eager-transformer miss tail)
    def _dropout(a, key):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply1(_dropout, x, k, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    k = default_generator.split()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _ad(a, key):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef
    return apply1(_ad, x, k, name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Lookup (reference: operators/lookup_table_v2_op).

    ``sparse=True`` on the eager tape emits a SelectedRows gradient
    (framework/selected_rows.py) exactly like lookup_table's is_sparse —
    no dense zeros(vocab, dim) per backward.  Under jit the dense path is
    used regardless (XLA fuses the scatter; PS tier owns giant tables)."""
    if sparse:
        out = _sparse_embedding(x, weight, padding_idx)
        if out is not None:
            return out

    def _emb(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply1(_emb, x, weight, nondiff=(0,), name="embedding")


def _sparse_embedding(x, weight, padding_idx):
    """Eager row-sparse lookup: custom TapeNode whose pullback returns a
    SelectedRows (the lookup_table_grad SelectedRows branch,
    operators/lookup_table_v2_op.h).  Returns None when the sparse path
    does not apply (in-trace, non-leaf weight, grad off)."""
    import weakref

    from paddle_tpu.core import TapeNode, is_grad_enabled
    from paddle_tpu.framework.selected_rows import SelectedRows
    if not isinstance(weight, Tensor) or not isinstance(x, Tensor):
        return None
    ids = x._data
    warr = weight._data
    if isinstance(ids, jax.core.Tracer) or isinstance(warr, jax.core.Tracer):
        return None
    if weight._node is not None:
        # non-leaf weight: SelectedRows cannot flow through another
        # node's array-typed vjp — use the dense path
        return None
    iarr = ids.astype(jnp.int32)
    out = jnp.take(warr, iarr, axis=0)
    if padding_idx is not None:
        out = jnp.where((iarr == padding_idx)[..., None], 0.0, out)
    track = is_grad_enabled() and not weight.stop_gradient
    t = Tensor(out, stop_gradient=not track)
    if not track:
        return t
    height, dim = warr.shape

    def vjp_fn(cot):
        flat = cot.reshape(-1, dim)
        rows = iarr.reshape(-1)
        if padding_idx is not None:
            flat = jnp.where((rows == padding_idx)[:, None], 0.0, flat)
        return (SelectedRows(rows, flat, height),)

    node = TapeNode(vjp_fn, [weight], [weakref.ref(t)],
                    name="embedding_sparse", out_avals=[(out.shape,
                                                         out.dtype)])
    t._node = node
    t._out_index = 0
    t.is_leaf_ = False
    return t


def one_hot(x, num_classes, name=None):
    return apply1(lambda a: jax.nn.one_hot(a, num_classes), x, nondiff=(0,),
                  name="one_hot")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.numpy().tolist()
    pad = [int(p) for p in pad]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def _pad(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle NCHW convention: pad lists innermost spatial dims
            # [left, right, top, bottom, ...] applying to last dims first
            n_spatial = len(pad) // 2
            pairs = [(0, 0)] * nd
            if data_format in ("NCHW", "NCL", "NCDHW"):
                dims = list(range(nd - 1, nd - 1 - n_spatial, -1))
            else:
                dims = list(range(nd - 2, nd - 2 - n_spatial, -1))
            for i, d in enumerate(dims):
                pairs[d] = (pad[2 * i], pad[2 * i + 1])
        if jmode == "constant":
            return jnp.pad(a, pairs, mode=jmode, constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)
    return apply1(_pad, x, name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    mode = mode.lower()

    def _interp(a):
        nd = a.ndim
        n_spatial = nd - 2
        if channel_last:
            spatial_axes = list(range(1, nd - 1))
        else:
            spatial_axes = list(range(2, nd))
        in_sizes = [a.shape[ax] for ax in spatial_axes]
        if size is not None:
            s = size.numpy().tolist() if isinstance(size, Tensor) else size
            out_sizes = [int(v) for v in (s if isinstance(s, (list, tuple))
                                          else [s] * n_spatial)]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * n_spatial
            out_sizes = [int(i * f) for i, f in zip(in_sizes, sf)]
        if mode == "nearest":
            out = a
            for ax, (i_s, o_s) in zip(spatial_axes, zip(in_sizes, out_sizes)):
                idx = jnp.floor(jnp.arange(o_s) * (i_s / o_s)).astype(jnp.int32)
                out = jnp.take(out, idx, axis=ax)
            return out
        if mode in ("bilinear", "linear", "trilinear", "bicubic"):
            meth = "cubic" if mode == "bicubic" else "linear"
            if channel_last:
                new_shape = (a.shape[0],) + tuple(out_sizes) + (a.shape[-1],)
            else:
                new_shape = a.shape[:2] + tuple(out_sizes)
            if align_corners:
                # jax.image doesn't do align_corners; emulate with map_coordinates
                coords = []
                for i_s, o_s in zip(in_sizes, out_sizes):
                    if o_s == 1:
                        coords.append(jnp.zeros((o_s,)))
                    else:
                        coords.append(jnp.linspace(0, i_s - 1, o_s))
                mesh = jnp.meshgrid(*coords, indexing="ij")
                batch_axes = [ax for ax in range(nd) if ax not in spatial_axes]

                def interp_one(img):
                    return jax.scipy.ndimage.map_coordinates(
                        img, [m for m in mesh], order=1, mode="nearest")
                flat = jnp.moveaxis(a, spatial_axes,
                                    list(range(nd - n_spatial, nd)))
                lead_shape = flat.shape[:nd - n_spatial]
                flat2 = flat.reshape((-1,) + flat.shape[nd - n_spatial:])
                out = jax.vmap(interp_one)(flat2)
                out = out.reshape(lead_shape + tuple(out_sizes))
                return jnp.moveaxis(out, list(range(nd - n_spatial, nd)),
                                    spatial_axes)
            return jax.image.resize(a, new_shape, method=meth)
        if mode == "area":
            # adaptive average pooling
            out = a
            for ax, o_s in zip(spatial_axes, out_sizes):
                i_s = out.shape[ax]
                if i_s % o_s == 0:
                    kk = i_s // o_s
                    shp = out.shape[:ax] + (o_s, kk) + out.shape[ax + 1:]
                    out = jnp.mean(out.reshape(shp), axis=ax + 1)
                else:
                    idx = jnp.floor(jnp.arange(o_s) * (i_s / o_s)).astype(
                        jnp.int32)
                    out = jnp.take(out, idx, axis=ax)
            return out
        raise ValueError(f"unsupported interpolate mode {mode}")
    return apply1(_interp, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: operators/math/im2col) — used by fold/unfold API."""
    def _t(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _t(kernel_sizes)
    sh, sw = _t(strides)
    dh, dw = _t(dilations)
    if isinstance(paddings, int):
        pads = (paddings,) * 4
    elif len(paddings) == 2:
        pads = (paddings[0], paddings[0], paddings[1], paddings[1])
    else:
        pads = tuple(paddings)

    def _unfold(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])])
        out_h = (a.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (a.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                sl = a[:, :, i * dh: i * dh + out_h * sh: sh,
                       j * dw: j * dw + out_w * sw: sw]
                patches.append(sl)
        stacked = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
        return stacked.reshape(n, c * kh * kw, out_h * out_w)
    return apply1(_unfold, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    def _t(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    oh, ow = _t(output_sizes)
    kh, kw = _t(kernel_sizes)
    sh, sw = _t(strides)
    dh, dw = _t(dilations)
    ph, pw = _t(paddings) if not isinstance(paddings, int) else (paddings,
                                                                paddings)

    def _fold(a):
        n, ckk, l = a.shape
        c = ckk // (kh * kw)
        out_h = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        out_w = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        a = a.reshape(n, c, kh, kw, out_h, out_w)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh: i * dh + out_h * sh: sh,
                             j * dw: j * dw + out_w * sw: sw].add(
                    a[:, :, i, j])
        return out[:, :, ph: ph + oh, pw: pw + ow]
    return apply1(_fold, x, name="fold")


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bilinear(a, b, w, *mb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if mb:
            out = out + mb[0]
        return out
    if bias is not None:
        return apply1(_bilinear, x1, x2, weight, bias, name="bilinear")
    return apply1(_bilinear, x1, x2, weight, name="bilinear")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def _cs(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply1(_cs, x1, x2, name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def _pd(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return apply1(_pd, x, y, name="pairwise_distance")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def _ps(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)
    return apply1(_ps, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def _pu(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
        return a.reshape(n, c * r * r, h // r, w // r)
    return apply1(_pu, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def _cs(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        a = jnp.transpose(a, (0, 2, 1, 3, 4))
        return a.reshape(n, c, h, w)
    return apply1(_cs, x, name="channel_shuffle")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k
    if prior_dist is not None:
        return apply1(_ls, label, prior_dist, name="label_smooth")
    return apply1(_ls, label, name="label_smooth")


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError(
        "class_center_sample: PS-style class sampling is provided by "
        "paddle_tpu.distributed.ps")
