"""Attention functionals.

The reference ships only full-materialised attention
(python/paddle/nn/layer/transformer.py:115 MultiHeadAttention) plus fused
inference kernels (operators/fused/multihead_matmul_op.cu).  The TPU-native
replacement is a Pallas flash-attention kernel (paddle_tpu/ops/pallas/
flash_attention.py) — blockwise online-softmax so the S×S score matrix never
hits HBM — with a pure-XLA fallback for CPU tests and odd shapes.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1
from paddle_tpu.framework import flags as _flags

__all__ = ["scaled_dot_product_attention", "flash_attention"]


def _xla_attention(q, k, v, mask, scale, causal):
    # q,k,v: (B, S, H, D) paddle layout
    qh = jnp.einsum("bshd->bhsd", q)
    kh = jnp.einsum("bshd->bhsd", k)
    vh = jnp.einsum("bshd->bhsd", v)
    scores = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool),
                               k=s_k - s_q)
        scores = jnp.where(causal_mask, scores, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -1e30)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
    return jnp.einsum("bhsd->bshd", out)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """(B, S, H, D) attention.  Uses the Pallas flash kernel on TPU when
    shapes allow — including masked calls: bool or additive ``attn_mask``
    broadcastable to (B, H, Sq, Sk) rides the kernel as a tile-streamed
    bias (the reference's fused attention takes the same bias_qk input,
    multihead_matmul_op.cu), so padded-batch workloads stay O(S·D).
    Falls back to the XLA path (still fused reasonably well by XLA, but
    materialises scores) for unsupported shapes/backends."""
    d = query.shape[-1]
    scale = 1.0 / math.sqrt(d)

    use_flash = False
    try:
        from paddle_tpu.ops.pallas import flash_attention as _fa
        use_flash = _fa.supported(
            tuple(query.shape), tuple(key.shape), attn_mask is None,
            causal=is_causal,
            bias_shape=None if attn_mask is None else tuple(attn_mask.shape))
    except Exception:
        use_flash = False

    if use_flash:
        from paddle_tpu.ops.pallas import flash_attention as _fa

        if attn_mask is not None:
            # padding masks are feed data: bias_grad=False skips the dbias
            # kernel and nondiff keeps them off the eager tape.  A LEARNED
            # additive bias (stop_gradient=False Tensor) keeps its grad —
            # the dbias reduction kernel serves it.
            trains = not getattr(attn_mask, "stop_gradient", True)

            def _run(q, k, v, m):
                return _fa.flash_attention(q, k, v, causal=is_causal,
                                           scale=scale, bias=m,
                                           bias_grad=trains)
            out = apply1(_run, query, key, value, attn_mask,
                         name="flash_attention",
                         nondiff=() if trains else (3,))
        else:
            def _run(q, k, v):
                return _fa.flash_attention(q, k, v, causal=is_causal,
                                           scale=scale)
            out = apply1(_run, query, key, value, name="flash_attention")
    else:
        def _run(q, k, v, *m):
            return _xla_attention(q, k, v, m[0] if m else None, scale,
                                  is_causal)
        if attn_mask is not None:
            out = apply1(_run, query, key, value, attn_mask,
                         name="sdp_attention")
        else:
            out = apply1(_run, query, key, value, name="sdp_attention")
    if dropout_p > 0.0 and training:
        from paddle_tpu.nn.functional.common import dropout
        out = dropout(out, p=dropout_p, training=training)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, attn_mask=None,
                    q_segment_ids=None, kv_segment_ids=None, name=None):
    """Flash attention with TPU-native extensions.

    ``q_segment_ids``/``kv_segment_ids`` ((B, S) int) enable
    packed-sequence attention — tokens only attend within their segment —
    at O(B·S) mask memory where an explicit packed mask is O(B·S²).  On
    the kernel path they are evaluated inside the Pallas tiles; the XLA
    fallback materialises the equivalent mask.
    """
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("flash_attention: pass both q_segment_ids and "
                         "kv_segment_ids, or neither")
    if q_segment_ids is not None:
        d = query.shape[-1]
        scale = 1.0 / math.sqrt(d)
        try:
            from paddle_tpu.ops.pallas import flash_attention as _fa
            ok = _fa.supported(
                tuple(query.shape), tuple(key.shape), attn_mask is None,
                causal=causal, segments=True,
                bias_shape=None if attn_mask is None
                else tuple(attn_mask.shape))
        except Exception:
            ok = False
        if ok:
            from paddle_tpu.ops.pallas import flash_attention as _fa

            def _run(q, k, v, qs, ks, *m):
                return _fa.flash_attention(
                    q, k, v, causal=causal, scale=scale,
                    bias=m[0] if m else None, bias_grad=False,
                    q_segment_ids=qs, kv_segment_ids=ks)
        else:
            def _run(q, k, v, qs, ks, *m):
                seg = (qs[:, None, :, None] == ks[:, None, None, :])
                mask = m[0] if m else None
                bias = jnp.where(seg, 0.0, -1e30)
                if mask is not None:
                    bias = bias + (jnp.where(mask, 0.0, -1e30)
                                   if mask.dtype == jnp.bool_ else mask)
                return _xla_attention(q, k, v, bias, scale, causal)
        args = [query, key, value, q_segment_ids, kv_segment_ids]
        nondiff = (3, 4)
        if attn_mask is not None:
            args.append(attn_mask)
            nondiff = (3, 4, 5)
        out = apply1(_run, *args, name="flash_attention", nondiff=nondiff)
        if dropout > 0.0:
            from paddle_tpu.nn.functional.common import dropout as _dropout
            out = _dropout(out, p=dropout)
        if return_softmax:
            return out, None
        return out

    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal, attn_mask=attn_mask)
    if return_softmax:
        return out, None
    return out
