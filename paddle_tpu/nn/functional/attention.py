"""Attention functionals.

The reference ships only full-materialised attention
(python/paddle/nn/layer/transformer.py:115 MultiHeadAttention) plus fused
inference kernels (operators/fused/multihead_matmul_op.cu).  The TPU-native
replacement is a Pallas flash-attention kernel (paddle_tpu/ops/pallas/
flash_attention.py) — blockwise online-softmax so the S×S score matrix never
hits HBM — with a pure-XLA fallback for CPU tests and odd shapes.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1
from paddle_tpu.framework import flags as _flags

__all__ = ["scaled_dot_product_attention", "flash_attention"]


def _xla_attention(q, k, v, mask, scale, causal):
    # q,k,v: (B, S, H, D) paddle layout
    qh = jnp.einsum("bshd->bhsd", q)
    kh = jnp.einsum("bshd->bhsd", k)
    vh = jnp.einsum("bshd->bhsd", v)
    scores = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool),
                               k=s_k - s_q)
        scores = jnp.where(causal_mask, scores, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -1e30)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
    return jnp.einsum("bhsd->bshd", out)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """(B, S, H, D) attention.  Uses the Pallas flash kernel on TPU when
    shapes allow, falling back to the XLA path (still fused reasonably well
    by XLA, but materialises scores)."""
    d = query.shape[-1]
    scale = 1.0 / math.sqrt(d)

    use_flash = False
    try:
        from paddle_tpu.ops.pallas import flash_attention as _fa
        use_flash = _fa.supported(tuple(query.shape), tuple(key.shape),
                                  attn_mask is None, causal=is_causal)
    except Exception:
        use_flash = False

    if use_flash:
        from paddle_tpu.ops.pallas import flash_attention as _fa

        def _run(q, k, v):
            return _fa.flash_attention(q, k, v, causal=is_causal, scale=scale)
        out = apply1(_run, query, key, value, name="flash_attention")
    else:
        def _run(q, k, v, *m):
            return _xla_attention(q, k, v, m[0] if m else None, scale,
                                  is_causal)
        if attn_mask is not None:
            out = apply1(_run, query, key, value, attn_mask,
                         name="sdp_attention")
        else:
            out = apply1(_run, query, key, value, name="sdp_attention")
    if dropout_p > 0.0 and training:
        from paddle_tpu.nn.functional.common import dropout
        out = dropout(out, p=dropout_p, training=training)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, name=None):
    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal)
    if return_softmax:
        return out, None
    return out
