"""Functional surface completions + fluid-era aliases.

The reference's ``paddle.nn.functional`` re-exports a long tail of
fluid.layers ops; the ones with their own kernels here:
  * grid_sample      — operators/grid_sampler_op.h (bilinear, zeros pad)
  * affine_grid      — operators/affine_grid_op.h
  * temporal_shift   — operators/temporal_shift_op.h (TSM video models)
  * bilinear_tensor_product — operators/bilinear_tensor_product_op.h
  * hsigmoid_loss    — operators/hierarchical_sigmoid_op.h (dense-path
    variant: the id tree is a complete binary tree over classes; the
    reference's custom-tree mode maps onto explicit path/ code inputs)
  * diag_embed, erf  — tensor kernels surfaced through functional
Pure re-exports (same op living elsewhere in this framework) are aliased
at the bottom — the reference does exactly this from fluid.layers.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1

__all__ = ["grid_sample", "affine_grid", "temporal_shift",
           "linear_chain_crf", "viterbi_decode",
           "bilinear_tensor_product", "hsigmoid_loss", "diag_embed", "erf",
           # aliases
           "roi_align", "roi_pool", "yolo_box", "prior_box", "box_coder",
           "image_resize", "resize_bilinear", "resize_nearest", "smooth_l1",
           "warpctc", "fc", "pool2d", "sequence_conv"]


def erf(x, name=None):
    return apply1(jax.scipy.special.erf, x, name="erf")


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """operators/diag_embed_op.h: batch of vectors -> batch of diagonal
    matrices."""
    def _d(a):
        n = a.shape[-1] + abs(offset)
        out_shape = a.shape[:-1] + (n, n)
        out = jnp.zeros(out_shape, a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two new axes at dim1/dim2
        order = []
        src = {d1: nd - 2, d2: nd - 1}
        it = iter(perm)
        for i in range(nd):
            order.append(src[i] if i in src else next(it))
        return jnp.transpose(out, order)
    return apply1(_d, input, name="diag_embed")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """operators/affine_grid_op.h: theta [N,2,3] + (N,C,H,W) -> sampling
    grid [N,H,W,2] in [-1,1] coords."""
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in out_shape.numpy()]
    N, _C, H, W = [int(v) for v in out_shape]

    def _base(n, align):
        if align:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    def _g(th):
        ys = _base(H, align_corners)
        xs = _base(W, align_corners)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)        # [H, W, 3]
        return jnp.einsum("hwk,njk->nhwj", base, th)     # [N, H, W, 2]
    return apply1(_g, theta, name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """operators/grid_sampler_op.h: sample NCHW input at grid [N,H',W',2]
    (xy in [-1,1]).  modes: bilinear/nearest; padding: zeros/border."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(mode)
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(padding_mode)

    def _unnorm(coord, size):
        if align_corners:
            return (coord + 1) / 2 * (size - 1)
        return ((coord + 1) * size - 1) / 2

    def _s(a, g):
        N, C, H, W = a.shape
        gx = _unnorm(g[..., 0], W)
        gy = _unnorm(g[..., 1], H)
        if padding_mode == "reflection":
            def refl(v, size):
                span = 2 * (size - 1) if align_corners else 2 * size
                v = jnp.abs(v) % (span if span > 0 else 1)
                return jnp.minimum(v, span - v)
            gx, gy = refl(gx, W), refl(gy, H)

        def gather(iy, ix):
            iyc = jnp.clip(iy, 0, H - 1)
            ixc = jnp.clip(ix, 0, W - 1)
            out = a[jnp.arange(N)[:, None, None], :, iyc, ixc]   # [N,h,w,C]
            if padding_mode == "zeros":
                valid = ((iy >= 0) & (iy < H) & (ix >= 0) &
                         (ix < W))[..., None]
                out = jnp.where(valid, out, 0.0)
            return out

        if mode == "nearest":
            out = gather(jnp.round(gy).astype(jnp.int32),
                         jnp.round(gx).astype(jnp.int32))
        else:
            x0 = jnp.floor(gx).astype(jnp.int32)
            y0 = jnp.floor(gy).astype(jnp.int32)
            wx = (gx - x0)[..., None]
            wy = (gy - y0)[..., None]
            out = (gather(y0, x0) * (1 - wx) * (1 - wy) +
                   gather(y0, x0 + 1) * wx * (1 - wy) +
                   gather(y0 + 1, x0) * (1 - wx) * wy +
                   gather(y0 + 1, x0 + 1) * wx * wy)
        return jnp.transpose(out, (0, 3, 1, 2))          # -> NCHW'
    return apply1(_s, x, grid, name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """operators/temporal_shift_op.h (TSM): [N*T, C, H, W]; first
    shift_ratio*C channels shift t-1, next block shifts t+1."""
    def _t(a):
        NT, C, H, W = a.shape
        T = seg_num
        n = NT // T
        v = a.reshape(n, T, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        pad = jnp.zeros((n, 1, C, H, W), a.dtype)
        fwd = jnp.concatenate([v[:, 1:], pad], axis=1)     # shift left
        bwd = jnp.concatenate([pad, v[:, :-1]], axis=1)    # shift right
        out = jnp.concatenate([fwd[:, :, :c1], bwd[:, :, c1:c2],
                               v[:, :, c2:]], axis=2)
        return out.reshape(NT, C, H, W)
    return apply1(_t, x, name="temporal_shift")


def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    """operators/bilinear_tensor_product_op.h: out[:, k] = x W_k y^T."""
    def _b(a, b, w, *rest):
        out = jnp.einsum("bi,kij,bj->bk", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x, y, weight) + ((bias,) if bias is not None else ())
    return apply1(_b, *args, name="bilinear_tensor_product")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """operators/hierarchical_sigmoid_op.h, default-tree mode: classes sit
    at the leaves of a complete binary tree with num_classes-1 internal
    nodes; the loss is sum of binary cross-entropies along the root→leaf
    path.  Custom trees pass path_table [N, L] (internal-node ids, -1 pad)
    and path_code [N, L] (0/1 branch codes)."""
    import numpy as np
    if path_table is None:
        nc = int(num_classes)
        depth = max(1, math.ceil(math.log2(max(nc, 2))))
        table = np.full((nc, depth), -1, np.int64)
        code = np.zeros((nc, depth), np.int64)
        for cls in range(nc):
            node = cls + (1 << depth)         # leaf id in implicit heap
            path = []
            while node > 1:
                parent = node // 2
                path.append((parent - 1, node % 2))
                node = parent
            for d, (nid, bit) in enumerate(reversed(path)):
                if nid < nc - 1:
                    table[cls, d] = nid
                    code[cls, d] = bit
        table_t = Tensor(jnp.asarray(table))
        code_t = Tensor(jnp.asarray(code))

        def _h(a, lbl, w, tbl, cd, *rest):
            t = jnp.take(tbl, lbl.astype(jnp.int32), axis=0)  # [N, L]
            c = jnp.take(cd, lbl.astype(jnp.int32), axis=0)
            valid = t >= 0
            tc = jnp.maximum(t, 0)
            wp = jnp.take(w, tc, axis=0)                      # [N, L, D]
            logits = jnp.einsum("nld,nd->nl", wp, a)
            if rest:
                logits = logits + jnp.take(rest[0], tc, axis=0)
            # bce with code as target (code 1 = right branch)
            lp = jax.nn.log_sigmoid(logits)
            ln = jax.nn.log_sigmoid(-logits)
            loss = -(c * lp + (1 - c) * ln)
            return jnp.sum(jnp.where(valid, loss, 0.0),
                           axis=1, keepdims=True)
        args = (input, label, weight, table_t, code_t) + (
            (bias,) if bias is not None else ())
        return apply1(_h, *args, nondiff=(1, 3, 4), name="hsigmoid_loss")

    def _h2(a, tbl, cd, w, *rest):
        valid = tbl >= 0
        tc = jnp.maximum(tbl, 0).astype(jnp.int32)
        wp = jnp.take(w, tc, axis=0)
        logits = jnp.einsum("nld,nd->nl", wp, a)
        if rest:
            logits = logits + jnp.take(rest[0], tc, axis=0)
        lp = jax.nn.log_sigmoid(logits)
        ln = jax.nn.log_sigmoid(-logits)
        loss = -(cd * lp + (1 - cd) * ln)
        return jnp.sum(jnp.where(valid, loss, 0.0), axis=1, keepdims=True)
    args = (input, path_table, path_code, weight) + (
        (bias,) if bias is not None else ())
    return apply1(_h2, *args, nondiff=(1, 2), name="hsigmoid_loss")


# ---------------------------------------------------------------------------
# aliases: same capability living elsewhere in the framework
# ---------------------------------------------------------------------------

def _alias(modpath, attr):
    def fn(*args, **kwargs):
        import importlib
        mod = importlib.import_module(modpath)
        return getattr(mod, attr)(*args, **kwargs)
    fn.__name__ = attr
    fn.__doc__ = f"alias of {modpath}.{attr}"
    return fn


roi_align = _alias("paddle_tpu.vision.ops", "roi_align")
roi_pool = _alias("paddle_tpu.vision.ops", "roi_pool")
yolo_box = _alias("paddle_tpu.vision.ops", "yolo_box")
prior_box = _alias("paddle_tpu.vision.ops", "prior_box")
box_coder = _alias("paddle_tpu.vision.ops", "box_coder")


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 **kw):
    from paddle_tpu.nn.functional.common import interpolate
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode=resample.lower())


def resize_bilinear(input, out_shape=None, scale=None, **kw):
    return image_resize(input, out_shape, scale, "BILINEAR")


def resize_nearest(input, out_shape=None, scale=None, **kw):
    return image_resize(input, out_shape, scale, "NEAREST")


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    from paddle_tpu.nn.functional.loss import smooth_l1_loss
    return smooth_l1_loss(x, y, reduction="none")


def warpctc(input, label, input_length=None, label_length=None, blank=0,
            norm_by_times=False):
    from paddle_tpu.nn.functional.loss import ctc_loss
    return ctc_loss(input, label, input_length, label_length, blank=blank)


def fc(x, size, num_flatten_dims=1, weight=None, bias=None, name=None):
    from paddle_tpu.nn.functional.common import linear
    if weight is None:
        raise ValueError("paddle_tpu fc is functional: pass weight "
                         "explicitly (the reference auto-creates params "
                         "in global scope, which does not exist here)")
    return linear(x, weight, bias)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, **kw):
    import paddle_tpu.nn.functional as F
    if global_pooling:
        pool_size = input.shape[2:]
    f = F.max_pool2d if pool_type == "max" else F.avg_pool2d
    return f(input, pool_size, pool_stride, pool_padding)


def sequence_conv(input, lengths, weight, bias=None, context_length=3,
                  padding=True, name=None):
    """operators/sequence_ops/sequence_conv_op.h on the padded-dense
    encoding: context-window features -> linear projection."""
    def _sc(a, lens, w, *rest):
        b, t, d = a.shape
        half = context_length // 2
        ctx = jnp.concatenate([jnp.zeros((b, half, d), a.dtype), a,
                               jnp.zeros((b, context_length - 1 - half, d),
                                         a.dtype)], axis=1)
        windows = jnp.concatenate(
            [ctx[:, i:i + t] for i in range(context_length)], axis=-1)
        out = jnp.einsum("btk,ko->bto", windows, w)
        if rest:
            out = out + rest[0]
        mask = (jnp.arange(t)[None, :] <
                lens.astype(jnp.int32)[:, None])[..., None]
        return jnp.where(mask, out, 0.0)
    args = (input, lengths, weight) + ((bias,) if bias is not None else ())
    return apply1(_sc, *args, nondiff=(1,), name="sequence_conv")


def linear_chain_crf(emission, transition, label, length=None, name=None):
    """Linear-chain CRF negative log-likelihood (reference:
    operators/linear_chain_crf_op.h).  Layout matches the reference:
    ``transition`` is [K+2, K] — row 0 start scores, row 1 stop scores,
    rows 2.. the [K, K] transition matrix.  Inputs are padded-dense:
    emission [B, T, K], label [B, T], length [B] (None = full T).
    Returns per-sequence NLL [B, 1]; differentiable in emission and
    transition (the forward algorithm is a lax.scan of logsumexps).
    """
    from paddle_tpu.core import Tensor as _T
    if length is None:
        import numpy as _np
        length = _T(jnp.full((emission.shape[0],), emission.shape[1],
                             jnp.int64))

    def _nll(em, trans, lbl, lens):
        B, T, K = em.shape
        start, stop, A = trans[0], trans[1], trans[2:]
        lbl = lbl.astype(jnp.int32)
        lens = lens.astype(jnp.int32)
        # -- partition function: forward algorithm over time ------------
        alpha0 = start[None, :] + em[:, 0]                    # [B, K]

        def step(alpha, t):
            nxt = jax.scipy.special.logsumexp(
                alpha[:, :, None] + A[None], axis=1) + em[:, t]
            keep = (t < lens)[:, None]
            return jnp.where(keep, nxt, alpha), None
        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        logZ = jax.scipy.special.logsumexp(alpha + stop[None, :], axis=1)
        # -- gold path score --------------------------------------------
        t_idx = jnp.arange(T)[None, :]
        valid = t_idx < lens[:, None]                         # [B, T]
        em_score = jnp.sum(jnp.where(
            valid, jnp.take_along_axis(em, lbl[:, :, None],
                                       axis=2)[:, :, 0], 0.0), axis=1)
        prev, nxt = lbl[:, :-1], lbl[:, 1:]
        trans_valid = t_idx[:, 1:] < lens[:, None]
        tr_score = jnp.sum(jnp.where(trans_valid, A[prev, nxt], 0.0),
                           axis=1)
        last = jnp.take_along_axis(lbl, (lens - 1)[:, None], axis=1)[:, 0]
        gold = em_score + tr_score + start[lbl[:, 0]] + stop[last]
        return (logZ - gold)[:, None]
    return apply1(_nll, emission, transition, label, length,
                  nondiff=(2, 3), name="linear_chain_crf")


def viterbi_decode(emission, transition, length=None,
                   include_start_end_tag=True, name=None):
    """Viterbi best path (reference: operators/crf_decoding_op.h; also
    the paddle.text.viterbi_decode surface).  With
    ``include_start_end_tag=True`` the transition uses the same [K+2, K]
    layout as linear_chain_crf (row 0 start, row 1 stop); with False it
    is a plain [K, K] matrix and start/stop scores are zero.  Returns
    (scores [B], path [B, T]) with positions past each length zeroed."""
    from paddle_tpu.core import Tensor as _T
    if length is None:
        length = _T(jnp.full((emission.shape[0],), emission.shape[1],
                             jnp.int64))

    def _vit(em, trans, lens):
        B, T, K = em.shape
        if include_start_end_tag:
            start, stop, A = trans[0], trans[1], trans[2:]
        else:
            start = jnp.zeros((trans.shape[1],), trans.dtype)
            stop = start
            A = trans
        lens = lens.astype(jnp.int32)
        alpha0 = start[None, :] + em[:, 0]

        def step(alpha, t):
            cand = alpha[:, :, None] + A[None]            # [B, K, K]
            best = jnp.max(cand, axis=1) + em[:, t]
            bp = jnp.argmax(cand, axis=1).astype(jnp.int32)
            keep = (t < lens)[:, None]
            return jnp.where(keep, best, alpha), \
                jnp.where(keep, bp, jnp.broadcast_to(
                    jnp.arange(K, dtype=jnp.int32)[None, :], (B, K)))
        alpha, bps = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        final = alpha + stop[None, :]
        scores = jnp.max(final, axis=1)
        last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)

        # backtrace: walk bps [T-1, B, K] from each sequence's end
        def back(tag, bt):
            prev = bt[jnp.arange(tag.shape[0]), tag]
            return prev, tag
        _, tags_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
        # tags_rev[t] is the tag at t+1; prepend the traced first tag
        first = bps[0][jnp.arange(B), tags_rev[0]] if T > 1 else last_tag
        # simpler: recompute full path via scan carrying position masks
        path = jnp.concatenate(
            [first[None] if T > 1 else last_tag[None],
             tags_rev.reshape(T - 1, B) if T > 1 else
             jnp.zeros((0, B), jnp.int32)], axis=0).T      # [B, T]
        t_idx = jnp.arange(T)[None, :]
        return scores, jnp.where(t_idx < lens[:, None], path, 0)
    from paddle_tpu.core import apply
    scores, path = apply(_vit, emission, transition, length, nondiff=(2,),
                         name="viterbi_decode")
    path.stop_gradient = True
    return scores, path
