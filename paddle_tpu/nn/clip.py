"""Gradient clipping (parity: python/paddle/fluid/clip.py — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            garr = g._data if isinstance(g, Tensor) else g
            out.append((p, Tensor(jnp.clip(garr, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            garr = g._data if isinstance(g, Tensor) else g
            norm = jnp.sqrt(jnp.sum(garr * garr))
            scale = jnp.where(norm > self.clip_norm, self.clip_norm /
                              jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor(garr * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq_sum = 0.0
        arrs = []
        for p, g in params_grads:
            garr = g._data if isinstance(g, Tensor) else g
            arrs.append((p, garr))
            if garr is not None:
                sq_sum = sq_sum + jnp.sum(
                    garr.astype(jnp.float32) ** 2)
        global_norm = jnp.sqrt(sq_sum)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12),
                            1.0)
        return [(p, Tensor((garr * scale).astype(garr.dtype))
                 if garr is not None else None) for p, garr in arrs]

    def functional_clip(self, grads: dict) -> dict:
        """Pure pytree variant used by jitted train steps."""
        import jax
        sq = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                 for g in jax.tree_util.tree_leaves(grads))
        gn = jnp.sqrt(sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        return jax.tree_util.tree_map(
            lambda g: (g * scale).astype(g.dtype), grads)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    from paddle_tpu.framework.selected_rows import SelectedRows
    for p in parameters:
        if isinstance(getattr(p, "_grad", None), SelectedRows):
            p._grad = Tensor(p._grad.to_dense())   # clip is a dense op
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data) ** norm_type) for g in grads])) ** (
                1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad._data = p._grad._data * scale
    return Tensor(total)
