"""nn.utils — weight_norm/spectral_norm wrappers + clip helpers
(parity: python/paddle/nn/utils/)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (reference:
    python/paddle/nn/utils/weight_norm_hook.py)."""
    from paddle_tpu.core import Parameter
    w = getattr(layer, name)
    d = dim if dim is not None else -1
    axes = tuple(i for i in range(w.ndim) if i != (dim if dim is not None else 0))
    norm = jnp.sqrt(jnp.sum(np.asarray(w._data) ** 2, axis=axes, keepdims=True))
    g = Parameter(jnp.asarray(norm), name=f"{name}_g")
    v = Parameter(w._data, name=f"{name}_v")
    delattr(layer, name)
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", v)

    def hook(lyr, inputs):
        from paddle_tpu.core import apply1
        gg = getattr(lyr, f"{name}_g")
        vv = getattr(lyr, f"{name}_v")
        axes2 = tuple(i for i in range(vv.ndim)
                      if i != (dim if dim is not None else 0))

        def _wn(gv, vval):
            nrm = jnp.sqrt(jnp.sum(vval * vval, axis=axes2, keepdims=True))
            return gv * vval / jnp.maximum(nrm, 1e-12)
        w_new = apply1(_wn, gg, vv, name="weight_norm")
        object.__setattr__(lyr, name, w_new)
    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    # materialise once so attribute exists before first call
    hook(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    from paddle_tpu.core import Parameter
    g = getattr(layer, f"{name}_g")
    v = getattr(layer, f"{name}_v")
    axes = tuple(range(1, v.ndim))
    nrm = jnp.sqrt(jnp.sum(v._data ** 2, axis=axes, keepdims=True))
    w = Parameter(g._data * v._data / jnp.maximum(nrm, 1e-12), name=name)
    delattr(layer, f"{name}_g")
    delattr(layer, f"{name}_v")
    if hasattr(layer, "_weight_norm_handle"):
        layer._weight_norm_handle.remove()
    if name in layer.__dict__:
        del layer.__dict__[name]
    layer.add_parameter(name, w)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from paddle_tpu.nn.layer.norm import SpectralNorm
    w = getattr(layer, name)
    sn = SpectralNorm(w.shape, dim=dim or 0, power_iters=n_power_iterations,
                      eps=eps)
    layer.add_sublayer(f"{name}_spectral_norm", sn)

    def hook(lyr, inputs):
        base = lyr._parameters.get(f"{name}_orig")
        w_new = sn(base)
        object.__setattr__(lyr, name, w_new)
    orig = getattr(layer, name)
    delattr(layer, name)
    layer.add_parameter(f"{name}_orig", orig)
    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    from paddle_tpu.tensor.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec[offset:offset + n].reshape(p.shape))
        offset += n
