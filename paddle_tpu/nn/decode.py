"""Seq2seq decoding — Decoder / BeamSearchDecoder / dynamic_decode.

Reference: python/paddle/fluid/layers/rnn.py (Decoder :800,
BeamSearchDecoder :866, dynamic_decode :1581) + the gather_tree op
(operators/gather_tree_op.h) used to backtrace beams.

TPU notes: decoding is inference with data-dependent termination; the
loop here is a host loop over at most ``max_step_num`` fused cell steps
(each step is one XLA computation over the [batch*beam, ...] state),
which matches how the reference's while_op executes it.  States are kept
beam-major ``[batch*beam, ...]`` exactly like the reference's
tile_beam_merge_with_batch convention.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode", "gather_tree"]


class Decoder:
    """Abstract decoding contract (rnn.py:800): initialize/step/finalize."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class _BeamState(NamedTuple):
    cell_states: object          # pytree of [B*beam, ...] Tensors
    log_probs: np.ndarray        # [B, beam]
    finished: np.ndarray         # [B, beam] bool
    lengths: np.ndarray          # [B, beam]


def gather_tree(ids, parents):
    """operators/gather_tree_op.h: backtrace [T, B, beam] step ids +
    parent-beam indices into final sequences."""
    ids = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids)
    parents = np.asarray(parents.numpy() if isinstance(parents, Tensor)
                         else parents)
    T, B, W = ids.shape
    out = np.zeros_like(ids)
    for b in range(B):
        for w in range(W):
            parent = w
            for t in range(T - 1, -1, -1):
                out[t, b, w] = ids[t, b, parent]
                parent = parents[t, b, parent]
    return out


class BeamSearchDecoder(Decoder):
    """rnn.py:866.  ``cell(inputs, states) -> (out, new_states)``;
    ``embedding_fn`` maps ids -> cell inputs; ``output_fn`` maps cell
    output -> vocab logits."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn or (lambda ids: ids)
        self.output_fn = output_fn or (lambda x: x)

    # -- beam-major helpers --------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (rnn.py:933) — for tensors the cell
        closes over, e.g. attention memory."""
        arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        tiled = jnp.repeat(arr, beam_size, axis=0)
        return Tensor(tiled)

    def _tile(self, tree):
        import jax
        return jax.tree_util.tree_map(
            lambda t: self.tile_beam_merge_with_batch(t, self.beam_size)
            if isinstance(t, Tensor) else t, tree,
            is_leaf=lambda t: isinstance(t, Tensor))

    def initialize(self, initial_cell_states):
        import jax
        leaves = [t for t in jax.tree_util.tree_leaves(
            initial_cell_states) if isinstance(t, Tensor)]
        batch = int(leaves[0].shape[0])
        states = self._tile(initial_cell_states)
        ids = np.full((batch * self.beam_size,), self.start_token, np.int64)
        inputs = self.embedding_fn(Tensor(jnp.asarray(ids)))
        log_probs = np.full((batch, self.beam_size), -1e9, np.float32)
        log_probs[:, 0] = 0.0                 # only beam 0 live at t=0
        return inputs, _BeamState(states, log_probs,
                                  np.zeros((batch, self.beam_size), bool),
                                  np.zeros((batch, self.beam_size),
                                           np.int64))

    def step(self, time, inputs, state: _BeamState):
        import jax
        W = self.beam_size
        cell_out, next_cell_states = self.cell(inputs, state.cell_states)
        logits = self.output_fn(cell_out)
        logits_np = np.asarray(
            (logits._data if isinstance(logits, Tensor) else logits),
            np.float32)
        BW, V = logits_np.shape
        B = BW // W
        step_lp = jax.nn.log_softmax(jnp.asarray(logits_np), axis=-1)
        step_lp = np.asarray(step_lp).reshape(B, W, V)
        # finished beams only extend with end_token at zero cost
        # (rnn.py _beam_search_step's noend mask)
        fin = state.finished[:, :, None]
        mask = np.full((1, 1, V), -1e9, np.float32)
        mask[0, 0, self.end_token] = 0.0
        step_lp = np.where(fin, mask, step_lp)
        total = state.log_probs[:, :, None] + step_lp       # [B, W, V]
        flat = total.reshape(B, W * V)
        top = np.argpartition(-flat, W, axis=1)[:, :W]
        # order the W winners by score (argpartition is unordered)
        order = np.argsort(-np.take_along_axis(flat, top, 1), axis=1)
        top = np.take_along_axis(top, order, 1)
        new_lp = np.take_along_axis(flat, top, 1)           # [B, W]
        parent = (top // V).astype(np.int64)
        token = (top % V).astype(np.int64)
        finished = np.take_along_axis(state.finished, parent, 1) | (
            token == self.end_token)
        lengths = np.take_along_axis(state.lengths, parent, 1) + \
            (~np.take_along_axis(state.finished, parent, 1)).astype(np.int64)

        gather = (parent + np.arange(B)[:, None] * W).reshape(-1)

        def _sel(t):
            if not isinstance(t, Tensor):
                return t
            return Tensor(jnp.take(t._data, jnp.asarray(gather), axis=0))
        next_cell_states = jax.tree_util.tree_map(
            _sel, next_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        next_inputs = self.embedding_fn(
            Tensor(jnp.asarray(token.reshape(-1))))
        outputs = {"token": token, "parent": parent}
        return outputs, _BeamState(next_cell_states, new_lp, finished,
                                   lengths), next_inputs, finished

    def finalize(self, outputs, final_state: _BeamState, sequence_lengths):
        ids = np.stack([o["token"] for o in outputs])       # [T, B, W]
        parents = np.stack([o["parent"] for o in outputs])
        seqs = gather_tree(ids, parents)                    # [T, B, W]
        predicted = np.transpose(seqs, (1, 0, 2))           # [B, T, W]
        return Tensor(jnp.asarray(predicted)), final_state


def dynamic_decode(decoder: Decoder, inits=None, max_step_num: int = 100,
                   output_time_major: bool = False, return_length: bool =
                   False, **kwargs):
    """rnn.py:1581: run decoder.initialize/step until every sequence
    finishes or max_step_num.  Returns (outputs, final_states) plus
    sequence lengths when ``return_length``."""
    inputs, state = decoder.initialize(inits)
    outputs = []
    for t in range(max_step_num):
        out, state, inputs, finished = decoder.step(t, inputs, state)
        outputs.append(out)
        if np.asarray(finished).all():
            break
    final_out, final_state = decoder.finalize(outputs, state, state.lengths)
    if output_time_major and isinstance(final_out, Tensor):
        final_out = Tensor(jnp.swapaxes(final_out._data, 0, 1))
    if return_length:
        return final_out, final_state, Tensor(jnp.asarray(state.lengths))
    return final_out, final_state
