"""Pooling layers (parity: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _PoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = kwargs

    def extra_repr(self):
        return (f"kernel_size={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}")


class AvgPool1D(_PoolNd):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kwargs.items()
                               if k in ("exclusive", "ceil_mode")})


class AvgPool2D(_PoolNd):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kwargs.items()
                               if k in ("exclusive", "ceil_mode",
                                        "data_format")})


class AvgPool3D(_PoolNd):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kwargs.items()
                               if k in ("exclusive", "ceil_mode",
                                        "data_format")})


class MaxPool1D(_PoolNd):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kwargs.items()
                               if k in ("return_mask",)})


class MaxPool2D(_PoolNd):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **{k: v for k, v in self.kwargs.items()
                               if k in ("ceil_mode", "data_format",
                                        "return_mask")})


class MaxPool3D(_PoolNd):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


class _AdaptivePoolNd(Layer):
    def __init__(self, output_size, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.kwargs = kwargs


class AdaptiveAvgPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool2d(
            x, self.output_size,
            **{k: v for k, v in self.kwargs.items()
               if k in ("data_format",)})


class AdaptiveAvgPool3D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)
