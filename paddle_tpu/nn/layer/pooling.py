"""Pooling layers (parity: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _PoolNd(Layer):
    # kwargs the functional actually honors; anything else raises instead of
    # being silently dropped (NDHWC data would otherwise pool the wrong axes)
    _allowed = ("name",)

    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        unsupported = set(kwargs) - set(self._allowed)
        if unsupported:
            raise ValueError(
                f"{type(self).__name__} does not support kwargs "
                f"{sorted(unsupported)}")
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def extra_repr(self):
        return (f"kernel_size={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}")


class AvgPool1D(_PoolNd):
    _allowed = ("exclusive", "ceil_mode", "data_format", "name")

    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool2D(_PoolNd):
    _allowed = ("exclusive", "ceil_mode", "divisor_override", "data_format",
                "name")

    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class AvgPool3D(_PoolNd):
    _allowed = ("exclusive", "ceil_mode", "divisor_override", "data_format",
                "name")

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool1D(_PoolNd):
    _allowed = ("return_mask", "ceil_mode", "data_format", "name")

    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool2D(_PoolNd):
    _allowed = ("return_mask", "ceil_mode", "data_format", "name")

    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class MaxPool3D(_PoolNd):
    _allowed = ("return_mask", "ceil_mode", "data_format", "name")

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            **self.kwargs)


class _AdaptivePoolNd(Layer):
    # kwargs the functional actually honors; anything else raises instead of
    # being silently dropped (NDHWC data would otherwise pool the wrong axes)
    _allowed = ("name",)

    def __init__(self, output_size, **kwargs):
        super().__init__()
        unsupported = set(kwargs) - set(self._allowed)
        if unsupported:
            raise ValueError(
                f"{type(self).__name__} does not support kwargs "
                f"{sorted(unsupported)}")
        self.output_size = output_size
        self.kwargs = {k: v for k, v in kwargs.items() if k != "name"}


class AdaptiveAvgPool1D(_AdaptivePoolNd):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePoolNd):
    _allowed = ("data_format", "name")

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, **self.kwargs)


class AdaptiveAvgPool3D(_AdaptivePoolNd):
    _allowed = ("data_format", "name")

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, **self.kwargs)


class AdaptiveMaxPool1D(_AdaptivePoolNd):
    _allowed = ("return_mask", "name")

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, **self.kwargs)


class AdaptiveMaxPool2D(_AdaptivePoolNd):
    _allowed = ("return_mask", "name")

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, **self.kwargs)


class AdaptiveMaxPool3D(_AdaptivePoolNd):
    _allowed = ("return_mask", "name")

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, **self.kwargs)
