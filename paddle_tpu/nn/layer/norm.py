"""Norm layers (parity: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as buffers (``_mean``/``_variance`` names match
the reference's state-dict keys, nn/layer/norm.py _BatchNormBase) so
checkpoints round-trip.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.core import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.initializer import Constant, _create_param
from paddle_tpu.nn.layer.common import ParamAttr
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = _create_param(
                [num_features], "float32", attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = _create_param(
                [num_features], "float32", attr=ParamAttr._to_attr(bias_attr),
                is_bias=True, default_initializer=Constant(0.0))
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(np.zeros(num_features, np.float32)))
        self.register_buffer("_variance",
                             Tensor(np.ones(num_features, np.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid.dygraph.BatchNorm signature support."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (reference: operators/sync_batch_norm_op.cu).

    Under pjit/shard_map the batch axis is sharded; XLA computes the global
    batch statistics when the reduction spans the mesh axis — the layer peers
    with paddle_tpu.distributed to psum stats when inside shard_map."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub._num_features, sub._momentum,
                                    sub._epsilon,
                                    data_format=sub._data_format)
                if sub.weight is not None:
                    new.weight.set_value(sub.weight)
                if sub.bias is not None:
                    new.bias.set_value(sub.bias)
                new._mean.set_value(sub._mean)
                new._variance.set_value(sub._variance)
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = _create_param(
                self._normalized_shape, "float32",
                attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = _create_param(
                self._normalized_shape, "float32",
                attr=ParamAttr._to_attr(bias_attr), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = _create_param(
                [num_channels], "float32", attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = _create_param(
                [num_channels], "float32", attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = _create_param(
                [num_features], "float32", attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = _create_param(
                [num_features], "float32", attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """reference: operators/spectral_norm_op — power-iteration weight norm."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        import paddle_tpu.tensor.random as R
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = _create_param([h], dtype,
                                      default_initializer=None)
        self.weight_v = _create_param([w], dtype,
                                      default_initializer=None)

    def forward(self, weight):
        import jax.numpy as jnp
        from paddle_tpu.core import apply1
        dim, eps, iters = self._dim, self._eps, self._power_iters

        def _sn(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        out = apply1(_sn, weight, self.weight_u, self.weight_v,
                     nondiff=(1, 2), name="spectral_norm")
        return out
