"""Common layers (parity: python/paddle/nn/layer/common.py) + ParamAttr."""
from __future__ import annotations

from typing import Optional

import numpy as np

from paddle_tpu.core import Parameter, Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.initializer import (Constant, Initializer, Normal,
                                       XavierUniform, _create_param)
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["ParamAttr", "Linear", "Dropout", "Dropout2D", "Dropout3D",
           "AlphaDropout", "Embedding", "Flatten", "Upsample",
           "UpsamplingBilinear2D", "UpsamplingNearest2D", "Bilinear",
           "CosineSimilarity", "PairwiseDistance", "Pad1D", "Pad2D", "Pad3D",
           "ZeroPad2D", "Identity", "Unfold", "Fold", "PixelShuffle"]


class PixelShuffle(Layer):
    """reference: nn/layer/vision.py PixelShuffle (pixel_shuffle_op.h)."""

    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class ParamAttr:
    """Parity with paddle.ParamAttr (python/paddle/fluid/param_attr.py)."""

    def __init__(self, name=None, initializer: Optional[Initializer] = None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None or isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return False
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        raise TypeError(f"bad ParamAttr spec {attr!r}")


class Linear(Layer):
    """y = xW + b with W: (in_features, out_features) — reference:
    python/paddle/nn/layer/common.py Linear, kernel matmul_v2_op."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = _create_param(
            [in_features, out_features], "float32",
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=XavierUniform())
        if bias_attr is not False:
            self.bias = _create_param(
                [out_features], "float32", attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    """reference: nn/layer/common.py Embedding + lookup_table_v2 kernel."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (padding_idx if padding_idx is None or
                             padding_idx >= 0 else num_embeddings + padding_idx)
        self._sparse = sparse
        self.weight = _create_param(
            [num_embeddings, embedding_dim], "float32",
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=XavierUniform())
        if self._padding_idx is not None:
            self.weight._data = self.weight._data.at[self._padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx,
                           sparse=self._sparse)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = _create_param(
            [out_features, in1_features, in2_features], "float32",
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=XavierUniform())
        if bias_attr is not False:
            self.bias = _create_param([out_features], "float32",
                                      attr=ParamAttr._to_attr(bias_attr),
                                      is_bias=True)
        else:
            self.bias = None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else \
            [padding, padding]
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        if isinstance(padding, int):
            padding = [padding, padding]
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        if isinstance(padding, int):
            padding = [padding] * 4
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        if isinstance(padding, int):
            padding = [padding] * 6
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)
