"""RNN layers (parity: python/paddle/nn/layer/rnn.py; reference kernel:
operators/rnn_op + cudnn path).

TPU-first: the time loop is a single ``lax.scan`` per direction per layer —
one compiled XLA while-loop with the cell body fused, instead of the
reference's per-step kernel launches / cuDNN descriptor machinery.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply
from paddle_tpu.nn import functional as F
from paddle_tpu.nn.initializer import Uniform, _create_param
from paddle_tpu.nn.layer.common import ParamAttr
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from paddle_tpu.tensor.creation import full
        b = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(
                shape[0], (list, tuple)):
            return tuple(full([b] + list(s), init_value) for s in shape)
        return full([b] + list(shape), init_value)


def _cell_params(cls, input_size, hidden_size, gates, weight_ih_attr,
                 weight_hh_attr, bias_ih_attr, bias_hh_attr):
    std = 1.0 / np.sqrt(hidden_size)
    init = Uniform(-std, std)
    w_ih = _create_param([gates * hidden_size, input_size], "float32",
                         attr=ParamAttr._to_attr(weight_ih_attr),
                         default_initializer=init)
    w_hh = _create_param([gates * hidden_size, hidden_size], "float32",
                         attr=ParamAttr._to_attr(weight_hh_attr),
                         default_initializer=init)
    b_ih = None if bias_ih_attr is False else _create_param(
        [gates * hidden_size], "float32", attr=ParamAttr._to_attr(bias_ih_attr),
        default_initializer=init, is_bias=True)
    b_hh = None if bias_hh_attr is False else _create_param(
        [gates * hidden_size], "float32", attr=ParamAttr._to_attr(bias_hh_attr),
        default_initializer=init, is_bias=True)
    return w_ih, w_hh, b_ih, b_hh


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        (self.weight_ih, self.weight_hh,
         self.bias_ih, self.bias_hh) = _cell_params(
            type(self), input_size, hidden_size, 1, weight_ih_attr,
            weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def _step(x, h, w_ih, w_hh, *biases):
            z = x @ w_ih.T + h @ w_hh.T
            for b in biases:
                z = z + b
            return act(z)
        args = [inputs, states, self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            args.append(self.bias_ih)
        if self.bias_hh is not None:
            args.append(self.bias_hh)
        from paddle_tpu.core import apply1
        h = apply1(_step, *args, name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        (self.weight_ih, self.weight_hh,
         self.bias_ih, self.bias_hh) = _cell_params(
            type(self), input_size, hidden_size, 4, weight_ih_attr,
            weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def _step(x, hp, cp, w_ih, w_hh, *biases):
            z = x @ w_ih.T + hp @ w_hh.T
            for b in biases:
                z = z + b
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            cn = f * cp + i * g
            hn = o * jnp.tanh(cn)
            return hn, cn
        args = [inputs, h, c, self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            args.append(self.bias_ih)
        if self.bias_hh is not None:
            args.append(self.bias_hh)
        hn, cn = apply(_step, *args, name="lstm_cell")
        return hn, (hn, cn)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        (self.weight_ih, self.weight_hh,
         self.bias_ih, self.bias_hh) = _cell_params(
            type(self), input_size, hidden_size, 3, weight_ih_attr,
            weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _step(x, hp, w_ih, w_hh, *biases):
            gi = x @ w_ih.T
            gh = hp @ w_hh.T
            if biases:
                gi = gi + biases[0]
                if len(biases) > 1:
                    gh = gh + biases[1]
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * hp
        args = [inputs, states, self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            args.append(self.bias_ih)
        if self.bias_hh is not None:
            args.append(self.bias_hh)
        from paddle_tpu.core import apply1
        h = apply1(_step, *args, name="gru_cell")
        return h, h


class RNN(Layer):
    """Run a cell over time (reference: nn/layer/rnn.py RNN) — lax.scan."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        outputs = []
        # python loop over time on the tape (correct everywhere);
        # the jitted fast path is the multi-layer LSTM/GRU below.
        x = inputs
        steps = x.shape[0] if self.time_major else x.shape[1]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = [None] * steps
        for t in order:
            xt = x[t] if self.time_major else x[:, t]
            o, states = self.cell(xt, states)
            outs[t] = o
        from paddle_tpu.tensor.manipulation import stack
        out = stack(outs, axis=0 if self.time_major else 1)
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        from paddle_tpu.tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent net over lax.scan.

    The whole stack runs as one jax computation via apply() — weights enter as
    differentiable tensor args, the scan is inside, so eager backward and jit
    capture both work.
    """

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, activation=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        gates = {"LSTM": 4, "GRU": 3}.get(self.MODE[:4].rstrip("_"), 1)
        if self.MODE.startswith("LSTM"):
            gates = 4
        elif self.MODE.startswith("GRU"):
            gates = 3
        else:
            gates = 1
        self._gates = gates
        self._num_dirs = num_dirs
        self.weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                w_ih, w_hh, b_ih, b_hh = _cell_params(
                    type(self), in_sz, hidden_size, gates, weight_ih_attr,
                    weight_hh_attr, bias_ih_attr, bias_hh_attr)
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                self.add_parameter(f"weight_ih{sfx}", w_ih)
                self.add_parameter(f"weight_hh{sfx}", w_hh)
                if b_ih is not None:
                    self.add_parameter(f"bias_ih{sfx}", b_ih)
                if b_hh is not None:
                    self.add_parameter(f"bias_hh{sfx}", b_hh)

    def _cell_fn(self):
        """Pre-projected step: the input projection ``x @ W_ihᵀ`` for ALL
        timesteps is hoisted out of the scan as one (T·B, in)·(in, G·H)
        matmul (the reference's fusion_lstm/fusion_gru optimization,
        operators/fused/fusion_lstm_op.cc:190 — "x·Wx for the whole batch
        before the recurrence"), so the scan body carries only the small
        h·W_hh recurrent matmul + gates.  ``step(carry, gi_t, w_hh,
        b_hh)`` consumes the pre-projected gate input."""
        mode = self.MODE

        def step(carry, gi, w_hh, b_hh):
            if mode.startswith("LSTM"):
                hp, cp = carry
                z = gi + hp @ w_hh.T + b_hh
                i, f, g, o = jnp.split(z, 4, axis=-1)
                i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                           jax.nn.sigmoid(o))
                g = jnp.tanh(g)
                cn = f * cp + i * g
                hn = o * jnp.tanh(cn)
                return (hn, cn), hn
            if mode.startswith("GRU"):
                hp = carry
                gh = hp @ w_hh.T + b_hh
                ir, iz, ic = jnp.split(gi, 3, axis=-1)
                hr, hz, hc = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(ir + hr)
                z = jax.nn.sigmoid(iz + hz)
                c = jnp.tanh(ic + r * hc)
                hn = (1 - z) * c + z * hp
                return hn, hn
            hp = carry
            act = jnp.tanh if mode.endswith("TANH") else jax.nn.relu
            hn = act(gi + hp @ w_hh.T + b_hh)
            return hn, hn
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        is_lstm = self.MODE.startswith("LSTM")
        L, D, H = self.num_layers, self._num_dirs, self.hidden_size
        step = self._cell_fn()
        time_major = self.time_major

        param_list = []
        for layer in range(L):
            for d in range(D):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                param_list += [getattr(self, f"weight_ih{sfx}"),
                               getattr(self, f"weight_hh{sfx}"),
                               getattr(self, f"bias_ih{sfx}"),
                               getattr(self, f"bias_hh{sfx}")]

        n_state = 2 if is_lstm else 1
        state_args = []
        if initial_states is not None:
            if is_lstm:
                state_args = [initial_states[0], initial_states[1]]
            else:
                state_args = [initial_states]

        def _run(x, *flat):
            params = flat[:4 * L * D]
            states = flat[4 * L * D:]
            if time_major:
                x = jnp.swapaxes(x, 0, 1)  # → batch-major internally? no: keep
            xt = x if not time_major else x
            seq = x if time_major else jnp.swapaxes(x, 0, 1)  # (T, B, F)
            b = seq.shape[1]
            if states:
                h0_all = states[0]
                c0_all = states[1] if is_lstm else None
            else:
                h0_all = jnp.zeros((L * D, b, H), seq.dtype)
                c0_all = jnp.zeros((L * D, b, H), seq.dtype) if is_lstm else None
            out = seq
            h_final, c_final = [], []
            for layer in range(L):
                dir_outs = []
                for d in range(D):
                    idx = layer * D + d
                    w_ih, w_hh, b_ih, b_hh = params[4 * idx: 4 * idx + 4]
                    h0 = h0_all[idx]
                    carry0 = (h0, c0_all[idx]) if is_lstm else h0
                    seq_d = jnp.flip(out, axis=0) if d == 1 else out
                    # fusion_lstm/fusion_gru: one big input projection for
                    # every timestep before the recurrence
                    gi_seq = seq_d @ w_ih.T + b_ih       # (T, B, G·H)

                    def body(carry, gi_t, _w_hh=w_hh, _b_hh=b_hh):
                        return step(carry, gi_t, _w_hh, _b_hh)
                    carry_f, ys = jax.lax.scan(body, carry0, gi_seq)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    dir_outs.append(ys)
                    if is_lstm:
                        h_final.append(carry_f[0])
                        c_final.append(carry_f[1])
                    else:
                        h_final.append(carry_f)
                out = jnp.concatenate(dir_outs, axis=-1) if D == 2 else \
                    dir_outs[0]
            h_out = jnp.stack(h_final, axis=0)
            outputs = out if time_major else jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return outputs, h_out, jnp.stack(c_final, axis=0)
            return outputs, h_out

        results = apply(_run, inputs, *param_list, *state_args, name=self.MODE)
        if is_lstm:
            out, h, c = results
            return out, (h, c)
        out, h = results
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
