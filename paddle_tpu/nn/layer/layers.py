"""nn.Layer — the module base class.

Parity target: python/paddle/fluid/dygraph/layers.py (Layer.__call__ :888,
hooks :911, parameter/sublayer registries, state_dict).  TPU-first addition:
``functional_state`` / ``functional_call`` let paddle_tpu.jit trace a Layer as
a pure function over a param pytree (the role the reference's
ProgramDescTracer plays for @to_static, imperative/jit/program_desc_tracer.cc)
without AST rewriting.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from paddle_tpu.core import Parameter, Tensor, convert_dtype


class HookRemoveHelper:
    def __init__(self, hooks: dict, key: int):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = [0]
        self._full_name = name_scope or self.__class__.__name__.lower()

    # -- attribute interception --------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, value)
                elif isinstance(value, Tensor):
                    params[name].set_value(value)
                else:
                    params.pop(name)
                    object.__setattr__(self, name, value)
            elif layers is not None and name in layers and not isinstance(
                    value, Layer):
                layers.pop(name)
                object.__setattr__(self, name, value)
            elif (buffers is not None and name in buffers
                  and isinstance(value, Tensor)):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(
            self._sub_layers) + list(self._buffers)

    # -- call / forward -----------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id[0] += 1
        self._forward_pre_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id[0])

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id[0] += 1
        self._forward_post_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id[0])

    # -- registry -----------------------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        from paddle_tpu.nn.initializer import _create_param
        return _create_param(shape, dtype or self._dtype, attr=attr,
                             is_bias=is_bias,
                             default_initializer=default_initializer)

    # -- iteration ----------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer_prefix in self._traverse(prefix, include_sublayers):
            layer = name
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{layer_prefix}.{pname}" if layer_prefix else pname
                yield full, p

    def _traverse(self, prefix, include_sublayers):
        yield self, prefix
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._traverse(sub_prefix, include_sublayers)

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, sub in self.named_children():
            yield sub

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for layer, layer_prefix in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                full = f"{layer_prefix}.{bname}" if layer_prefix else bname
                yield full, b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def apply(self, fn: Callable):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        prefix = structured_name_prefix.rstrip(".")
        for name, p in self.named_parameters(
                prefix=prefix, include_sublayers=include_sublayers):
            dest[name] = p
        # exclude non-persistable buffers at every nesting level
        skip = set()
        for layer, layer_prefix in self._traverse(prefix, include_sublayers):
            for bname in layer._non_persistable_buffer_names:
                skip.add(f"{layer_prefix}.{bname}" if layer_prefix else bname)
        for name, b in self.named_buffers(
                prefix=prefix, include_sublayers=include_sublayers):
            if name in skip:
                continue
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            if list(v.shape) != list(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: {v.shape} vs {target.shape}")
            target.set_value(v.astype(target.dtype.name
                                      if v.dtype.kind == "f" else v.dtype))
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype/device movement ---------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for p in self.parameters():
                p._data = p._data.astype(dt)
            for _, b in self.named_buffers():
                if b is not None and b.dtype.kind == "f":
                    b._data = b._data.astype(dt)
            for layer in self.sublayers(include_self=True):
                layer._dtype = dt.name
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def full_name(self):
        return self._full_name

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = self.__class__.__name__ + "(" + extra
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    # -- functional bridge (TPU-first; used by paddle_tpu.jit) -------------
    def functional_state(self):
        """Return (params_dict, buffers_dict) of jax arrays keyed by
        structured names — the pytree that paddle_tpu.jit traces over."""
        params = {n: p._data for n, p in self.named_parameters()}
        buffers = {n: b._data for n, b in self.named_buffers() if b is not None}
        return params, buffers

    @contextlib.contextmanager
    def _swapped_state(self, params: Dict[str, object],
                       buffers: Optional[Dict[str, object]] = None):
        """Temporarily substitute raw arrays into the live parameters
        (torch.func.functional_call-style) so tracing sees pure inputs."""
        named_p = dict(self.named_parameters())
        named_b = dict(self.named_buffers())
        saved_p = {n: t._data for n, t in named_p.items()}
        saved_b = {n: t._data for n, t in named_b.items() if t is not None}
        saved_sg = {n: t.stop_gradient for n, t in named_p.items()}
        try:
            for n, arr in params.items():
                named_p[n]._data = arr
            if buffers:
                for n, arr in buffers.items():
                    if n in named_b and named_b[n] is not None:
                        named_b[n]._data = arr
            yield
        finally:
            for n, arr in saved_p.items():
                named_p[n]._data = arr
                named_p[n].stop_gradient = saved_sg[n]
            for n, arr in saved_b.items():
                named_b[n]._data = arr


class Sequential(Layer):
    """paddle.nn.Sequential parity."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and (
                len(layers[0]) == 2 and isinstance(layers[0][0], str)):
            layers = (layers[0],)
        for i, item in enumerate(layers):
            if isinstance(item, tuple):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers)
        self._sub_layers[keys[idx]] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        keys = list(self._parameters)
        return self._parameters[keys[idx]]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
