"""Activation layers (parity: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.initializer import Constant, _create_param
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["ReLU", "ReLU6", "ELU", "SELU", "CELU", "GELU", "Sigmoid",
           "Hardsigmoid", "Hardswish", "Hardtanh", "Hardshrink", "Softshrink",
           "Tanhshrink", "LeakyReLU", "PReLU", "LogSigmoid", "Maxout", "Silu",
           "Swish", "Mish", "Softmax", "LogSoftmax", "Softplus", "Softsign",
           "Tanh", "ThresholdedReLU", "GLU"]


def _simple(fname, cls_name, **defaults):
    class _Act(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **{k: v for k, v in kwargs.items()
                                           if k != "name"}}

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)
    _Act.__name__ = cls_name
    _Act.__qualname__ = cls_name
    return _Act


ReLU = _simple("relu", "ReLU")
ReLU6 = _simple("relu6", "ReLU6")
Sigmoid = _simple("sigmoid", "Sigmoid")
Hardswish = _simple("hardswish", "Hardswish")
Tanhshrink = _simple("tanhshrink", "Tanhshrink")
LogSigmoid = _simple("log_sigmoid", "LogSigmoid")
Silu = _simple("silu", "Silu")
Swish = _simple("swish", "Swish")
Mish = _simple("mish", "Mish")
Softsign = _simple("softsign", "Softsign")
Tanh = _simple("tanh", "Tanh")


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from paddle_tpu.nn.layer.common import ParamAttr
        self._data_format = data_format
        self.weight = _create_param(
            [num_parameters], "float32", attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)
