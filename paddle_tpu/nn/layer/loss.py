"""Loss layers (parity: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "SmoothL1Loss", "KLDivLoss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "CosineEmbeddingLoss",
           "CTCLoss", "SigmoidFocalLoss", "TripletMarginLoss",
           "SoftMarginLoss", "HSigmoidLoss", "NCELoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, normalizer=None,
                 reduction="sum", name=None):
        super().__init__()
        self.alpha, self.gamma = alpha, gamma
        self.normalizer = normalizer
        self.reduction = reduction

    def forward(self, logit, label):
        return F.sigmoid_focal_loss(logit, label, self.normalizer, self.alpha,
                                    self.gamma, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        m, p, e, s, r = self.args
        return F.triplet_margin_loss(input, positive, negative, m, p, e, s, r)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classification head (reference:
    nn/layer/loss.py HSigmoidLoss over hierarchical_sigmoid_op.h).
    Default complete-binary-tree mode; custom trees pass path_table /
    path_code to forward."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        import numpy as np

        from paddle_tpu.core import Parameter
        self.num_classes = num_classes
        self.is_custom = is_custom
        n_nodes = num_classes - 1
        std = 1.0 / max(1.0, feature_size ** 0.5)
        rng = np.random.default_rng(0)
        self.weight = Parameter(rng.uniform(
            -std, std, (n_nodes, feature_size)).astype(np.float32),
            name="hsigmoid_w")
        self.bias = None
        if bias_attr is not False:
            self.bias = Parameter(np.zeros((n_nodes,), np.float32),
                                  name="hsigmoid_b")

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


class NCELoss(Layer):
    """Noise-contrastive estimation head (reference: operators/nce_op.h +
    fluid.layers.nce): binary-classify the true class against
    num_neg_samples noise draws instead of a full-vocab softmax.

    Uniform sampler with the standard logQ correction: with q = 1/V,
    s'_c = s_c - log(k·q_c); loss = -log σ(s'_y) - Σ_i log(1-σ(s'_i)).
    The reference's custom_dist/log_uniform samplers map onto the
    ``sampler`` arg ('uniform' implemented; the fused path for giant
    vocabs is the PS/SelectedRows tier)."""

    def __init__(self, num_total_classes, dim, num_neg_samples=10,
                 sampler="uniform", weight_attr=None, bias_attr=None,
                 seed=0, name=None):
        super().__init__()
        import numpy as np

        from paddle_tpu.core import Parameter
        if sampler != "uniform":
            raise NotImplementedError("only the uniform sampler is "
                                      "implemented")
        self.num_total_classes = num_total_classes
        self.num_neg_samples = num_neg_samples
        rng = np.random.default_rng(seed)
        std = 1.0 / max(1.0, dim ** 0.5)
        self.weight = Parameter(rng.uniform(
            -std, std, (num_total_classes, dim)).astype(np.float32),
            name="nce_w")
        self.bias = None
        if bias_attr is not False:
            self.bias = Parameter(np.zeros((num_total_classes,),
                                           np.float32), name="nce_b")
        self._rng = rng

    def forward(self, input, label):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from paddle_tpu.core import Tensor, apply1
        k, V = self.num_neg_samples, self.num_total_classes
        b = int(input.shape[0])
        noise = Tensor(jnp.asarray(
            self._rng.integers(0, V, size=(b, k)).astype(np.int64)))
        log_kq = float(np.log(k / V))

        def _nce(x, lbl, noise_ids, w, *rest):
            lbl = lbl.reshape(-1).astype(jnp.int32)
            cand = jnp.concatenate([lbl[:, None],
                                    noise_ids.astype(jnp.int32)], axis=1)
            wc = jnp.take(w, cand, axis=0)               # [B, 1+k, D]
            s = jnp.einsum("bkd,bd->bk", wc, x)
            if rest:
                s = s + jnp.take(rest[0], cand, axis=0)
            s = s - log_kq
            pos = -jax.nn.log_sigmoid(s[:, 0])
            neg = -jnp.sum(jax.nn.log_sigmoid(-s[:, 1:]), axis=1)
            return (pos + neg)[:, None]
        args = (input, label, noise, self.weight) + (
            (self.bias,) if self.bias is not None else ())
        return apply1(_nce, *args, nondiff=(1, 2), name="nce_loss")
