"""Weight initializers (parity: python/paddle/nn/initializer/ +
python/paddle/fluid/initializer.py).

Initializers are callables shape×dtype→jax array, seeded from the global
Generator (paddle_tpu.tensor.random) so ``paddle_tpu.seed`` makes init
deterministic, like the reference's per-op seed attributes.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Parameter, Tensor, convert_dtype
from paddle_tpu.tensor.random import default_generator

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Dirac", "Orthogonal", "calculate_gain",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "selu": 3.0 / 4.0}
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity in gains:
        return gains[nonlinearity]
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


def _fan_in_out(shape: Sequence[int]):
    shape = list(shape)
    if len(shape) < 2:
        fan_in = fan_out = shape[0] if shape else 1
    elif len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        # conv kernels stored OIHW-style in the reference; receptive field =
        # prod of trailing dims
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(shape, self.value, dtype=convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        k = default_generator.split()
        return self.mean + self.std * jax.random.normal(
            k, shape, dtype=convert_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        k = default_generator.split()
        return self.mean + self.std * jax.random.truncated_normal(
            k, -2.0, 2.0, shape, dtype=convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        k = default_generator.split()
        return jax.random.uniform(k, shape, dtype=convert_dtype(dtype),
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = default_generator.split()
        return std * jax.random.normal(k, shape, dtype=convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = default_generator.split()
        return jax.random.uniform(k, shape, dtype=convert_dtype(dtype),
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        k = default_generator.split()
        return std * jax.random.normal(k, shape, dtype=convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        k = default_generator.split()
        return jax.random.uniform(k, shape, dtype=convert_dtype(dtype),
                                  minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        v = self.value
        if isinstance(v, Tensor):
            v = v.numpy()
        arr = jnp.asarray(np.asarray(v), dtype=convert_dtype(dtype))
        return arr.reshape(shape)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        arr = np.zeros(shape, dtype=np.float32)
        out_per_group = shape[0] // self.groups
        min_dim = min(out_per_group, shape[1])
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for d in range(min_dim):
                arr[(g * out_per_group + d, d) + tuple(centers)] = 1.0
        return jnp.asarray(arr, dtype=convert_dtype(dtype))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        k = default_generator.split()
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(k, (max(rows, cols), min(rows, cols)),
                                 dtype=jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(
            convert_dtype(dtype))


# legacy-name aliases (fluid.initializer)
ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign


def _create_param(shape, dtype, attr=None, is_bias=False,
                  default_initializer=None, name=None) -> Parameter:
    """Shared parameter factory (≈ LayerHelper.create_parameter,
    python/paddle/fluid/layer_helper_base.py)."""
    from paddle_tpu.nn.layer.common import ParamAttr
    shape = [int(s) for s in shape]
    init = default_initializer
    trainable = True
    regularizer = None
    lr = 1.0
    pname = name
    if isinstance(attr, ParamAttr):
        init = attr.initializer or init
        trainable = attr.trainable
        regularizer = attr.regularizer
        lr = attr.learning_rate
        pname = attr.name or pname
    elif attr is False:
        raise ValueError("_create_param called with attr=False")
    if init is None:
        init = Constant(0.0) if is_bias else XavierUniform()
    data = init(tuple(shape), dtype=dtype or "float32")
    p = Parameter(data, name=pname, trainable=trainable)
    p.regularizer = regularizer
    p.optimize_attr = {"learning_rate": lr}
    p.is_bias = is_bias
    return p
