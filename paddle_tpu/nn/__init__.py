"""paddle_tpu.nn — parity with python/paddle/nn/ (~20.4k LoC in reference)."""
from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.nn.layer.layers import (Layer, LayerList, ParameterList,  # noqa: F401
                                        Sequential)
from paddle_tpu.nn.layer.common import *  # noqa: F401,F403
from paddle_tpu.nn.layer.activation import *  # noqa: F401,F403
from paddle_tpu.nn.layer.conv import *  # noqa: F401,F403
from paddle_tpu.nn.layer.loss import *  # noqa: F401,F403
from paddle_tpu.nn.layer.norm import *  # noqa: F401,F403
from paddle_tpu.nn.layer.pooling import *  # noqa: F401,F403
from paddle_tpu.nn.layer.rnn import *  # noqa: F401,F403
from paddle_tpu.nn.layer.transformer import *  # noqa: F401,F403
from paddle_tpu.nn.decode import (  # noqa: F401
    BeamSearchDecoder, Decoder, dynamic_decode, gather_tree)
from paddle_tpu.nn.clip import (ClipGradByValue, ClipGradByNorm,  # noqa: F401
                                ClipGradByGlobalNorm)
from paddle_tpu.nn import utils  # noqa: F401
