"""Quantization (slim) tier — QAT fake-quant + post-training quantization.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
  * quantization_pass.py fake_quantize_abs_max /
    fake_quantize_moving_average_abs_max / channel-wise variants (the op
    kernels live in operators/fake_quantize_op.cc);
  * imperative/qat.py ImperativeQuantAware — swaps Linear/Conv2D for
    quantized counterparts that fake-quant weights + activations;
  * post_training_quantization.py — calibrate abs-max over sample data,
    then store int8 weights + scales.

TPU notes: int8 matmul on the MXU is not exposed through jax today, so
the *execution* of quantized layers stays bf16/fp32 with
quantize→dequantize applied (exactly what the reference's fake-quant
training path computes); the artifacts (int8 weights + scales from PTQ)
are the deployment contract.  Gradients flow via the straight-through
estimator: ``x + stop_gradient(q(x) - x)`` — identity backward, quantized
forward, matching fake_quantize_op's grad kernel.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["fake_quantize_dequantize_abs_max",
           "fake_channel_wise_quantize_dequantize_abs_max",
           "MovingAverageAbsMaxObserver", "QuantizedLinear",
           "ImperativeQuantAware", "quant_post_weights", "dequant_weights",
           "Int8InferenceLinear", "Int8InferenceConv2D",
           "convert_to_int8_inference"]


def _qmax(bits: int) -> float:
    return float(2 ** (bits - 1) - 1)


def _quant_act(a):
    """Dynamic per-tensor abs-max activation quantization — the single
    activation rule shared by the Int8Inference layers (same
    single-source-of-truth policy as _quantize_weight).  Returns
    (a_int8, scale)."""
    af = a.astype(jnp.float32)
    s_x = jnp.maximum(jnp.max(jnp.abs(af)), 1e-8) / 127.0
    return jnp.clip(jnp.round(af / s_x), -127, 127).astype(jnp.int8), s_x


def fake_quantize_dequantize_abs_max(x, bits: int = 8, name=None):
    """operators/fake_quantize_op.cc FakeQuantizeDequantizeAbsMax: scale =
    max|x|; straight-through gradient."""
    qm = _qmax(bits)

    def _q(a):
        scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
        q = jnp.round(a / scale * qm) / qm * scale
        return a + jax.lax.stop_gradient(q - a)
    return apply1(_q, x, name="fake_quant_dequant_abs_max")


def fake_channel_wise_quantize_dequantize_abs_max(x, bits: int = 8,
                                                  quant_axis: int = 0,
                                                  name=None):
    """Per-output-channel scales (fake_channel_wise_quantize_op) — the
    weight path of QAT conv/linear."""
    qm = _qmax(bits)

    def _q(a):
        axes = tuple(i for i in range(a.ndim) if i != quant_axis)
        scale = jnp.maximum(jnp.max(jnp.abs(a), axis=axes, keepdims=True),
                            1e-8)
        q = jnp.round(a / scale * qm) / qm * scale
        return a + jax.lax.stop_gradient(q - a)
    return apply1(_q, x, name="fake_channel_wise_quant")


class MovingAverageAbsMaxObserver:
    """fake_quantize_moving_average_abs_max state machine (rate 0.9) for
    activation scales."""

    def __init__(self, rate: float = 0.9):
        self.rate = rate
        self.scale: Optional[float] = None

    def update(self, x) -> float:
        cur = float(jnp.max(jnp.abs(
            x._data if isinstance(x, Tensor) else jnp.asarray(x))))
        self.scale = cur if self.scale is None else \
            self.rate * self.scale + (1 - self.rate) * cur
        return max(self.scale, 1e-8)

    def quantize(self, x, bits: int = 8):
        qm = _qmax(bits)
        scale = self.update(x)

        def _q(a):
            q = jnp.clip(jnp.round(a / scale * qm), -qm, qm) / qm * scale
            return a + jax.lax.stop_gradient(q - a)
        return apply1(_q, x, name="fake_quant_moving_avg")


class QuantizedLinear(Layer):
    """imperative/qat.py QuantizedLinear: fake-quant weight (channel-wise)
    and input activation (moving-average) around the dense matmul."""

    def __init__(self, linear, weight_bits: int = 8, activation_bits: int = 8):
        super().__init__()
        self.weight = linear.weight
        self.bias = getattr(linear, "bias", None)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._observer = MovingAverageAbsMaxObserver()

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        xq = self._observer.quantize(x, self.activation_bits)
        wq = fake_channel_wise_quantize_dequantize_abs_max(
            self.weight, self.weight_bits, quant_axis=1)
        return F.linear(xq, wq, self.bias)


class ImperativeQuantAware:
    """imperative/qat.py ImperativeQuantAware.quantize: in-place module
    swap Linear→QuantizedLinear (the reference also covers Conv2D; conv
    follows the same recipe via fake_channel_wise on axis 0)."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def quantize(self, model: Layer) -> Layer:
        from paddle_tpu.nn.layer.common import Linear
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, Linear):
                model._sub_layers[name] = QuantizedLinear(
                    child, self.weight_bits, self.activation_bits)
            else:
                self.quantize(child)
        return model


# ---------------------------------------------------------------------------
# post-training (weight) quantization
# ---------------------------------------------------------------------------

def quant_post_weights(model: Layer, bits: int = 8) -> Dict[str, dict]:
    """post_training_quantization.py weight path: per-channel int8 weights
    + float scales for every Linear weight; returns the deployment dict
    {param_name: {"int": int8 array, "scale": [out] scales}}."""
    out = {}
    for name, p in model.named_parameters():
        if p._data.ndim != 2 or not name.endswith("weight"):
            continue
        q, scale = _quantize_weight(np.asarray(p._data), bits)
        out[name] = {"int": q, "scale": scale}
    return out


def dequant_weights(packed: Dict[str, dict]) -> Dict[str, np.ndarray]:
    return {n: d["int"].astype(np.float32) * d["scale"]
            for n, d in packed.items()}


# ---------------------------------------------------------------------------
# int8 inference EXECUTION (round 4): the deployment tier that actually
# runs the quantized matmul, not just packs weights
# ---------------------------------------------------------------------------


class Int8InferenceLinear(Layer):
    """Linear executed as an int8×int8→int32 matmul (reference role:
    inference/tensorrt int8 + operators/fake_quantize followed by the
    quantized kernel; TPU-native: the MXU runs s8 matmuls at 2× the
    bf16 rate, so this is the idiomatic deployment path).

    Weights: per-out-channel symmetric int8 (from quant_post_weights).
    Activations: dynamic per-tensor abs-max (the reference's
    moving-average observer becomes a static scale when calibrated;
    dynamic is the calibration-free default).
    """

    def __init__(self, w_int8: np.ndarray, w_scale: np.ndarray, bias=None):
        super().__init__()
        self._w_q = jnp.asarray(w_int8, jnp.int8)          # (in, out)
        self._w_scale = jnp.asarray(w_scale, jnp.float32)  # (out,)
        self._bias = None if bias is None else jnp.asarray(
            np.asarray(bias), jnp.float32)

    def forward(self, x):
        wq, ws, b = self._w_q, self._w_scale, self._bias

        def _run(a):
            a_q, s_x = _quant_act(a)
            acc = jax.lax.dot_general(
                a_q, wq, (((a.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (s_x * ws)
            if b is not None:
                y = y + b
            return y.astype(a.dtype) if a.dtype != jnp.float32 else y
        from paddle_tpu.core import apply1
        return apply1(_run, x, name="int8_linear")


class Int8InferenceConv2D(Layer):
    """Conv2D executed as an s8 x s8 -> s32 convolution (the conv leg of
    the reference's int8 deployment tier — contrib/slim/ + the MKLDNN/
    TensorRT quantized conv kernels, inference/api/mkldnn_quantizer.cc;
    TPU-native: the MXU runs s8 convs at 2x the bf16 rate).

    Weights: per-OUT-CHANNEL symmetric int8 (scale over the (I, kh, kw)
    slice, the channel-wise rule of fake_channel_wise_quantize_op).
    Activations: dynamic per-tensor abs-max, like Int8InferenceLinear.
    NCHW layout (the vision zoo's default).
    """

    def __init__(self, w_int8: np.ndarray, w_scale: np.ndarray, bias=None,
                 stride=1, padding=0, dilation=1, groups: int = 1):
        super().__init__()
        from paddle_tpu.nn.functional.conv import _norm_padding, _tuplify
        self._w_q = jnp.asarray(w_int8, jnp.int8)          # (O, I, kh, kw)
        self._w_scale = jnp.asarray(w_scale, jnp.float32)  # (O,)
        self._bias = None if bias is None else jnp.asarray(
            np.asarray(bias), jnp.float32)
        # same normalization as F.conv2d, so any paddle padding spelling
        # (int, per-dim, pairs, SAME/VALID) behaves identically
        self._stride = _tuplify(stride, 2)
        self._padding = _norm_padding(padding, 2)
        self._dilation = _tuplify(dilation, 2)
        self._groups = int(groups)

    def forward(self, x):
        wq, ws, b = self._w_q, self._w_scale, self._bias
        strides, pad = self._stride, self._padding
        dil, groups = self._dilation, self._groups

        def _run(a):
            a_q, s_x = _quant_act(a)
            dn = jax.lax.conv_dimension_numbers(
                a.shape, wq.shape, ("NCHW", "OIHW", "NCHW"))
            acc = jax.lax.conv_general_dilated(
                a_q, wq, window_strides=strides, padding=pad,
                rhs_dilation=dil, dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (s_x * ws)[None, :, None, None]
            if b is not None:
                y = y + b[None, :, None, None]
            return y.astype(a.dtype) if a.dtype != jnp.float32 else y
        return apply1(_run, x, name="int8_conv2d")


def _quantize_weight(w: np.ndarray, bits: int = 8, out_axis: int = 1):
    """Per-out-channel symmetric int8 pack — the single source of truth
    shared by quant_post_weights (pack) and the Int8Inference layers
    (deploy) so the two paths can never diverge numerically.
    ``out_axis``: which axis holds output channels (1 for Linear's
    (in, out); 0 for Conv2D's (O, I, kh, kw))."""
    qm = _qmax(bits)
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != out_axis)
    scale = np.maximum(np.abs(w).max(axis=reduce_axes), 1e-8)
    shape = [1] * w.ndim
    shape[out_axis] = -1
    q = np.clip(np.round(w / scale.reshape(shape) * qm), -qm, qm) \
        .astype(np.int8)
    return q, (scale / qm).astype(np.float32)


def _int8_of(linear) -> "Int8InferenceLinear":
    q, scale = _quantize_weight(np.asarray(linear.weight._data))
    bias = linear.bias._data if getattr(linear, "bias", None) is not None \
        else None
    return Int8InferenceLinear(q, scale, bias)


def _int8_of_conv(conv) -> "Int8InferenceConv2D":
    q, scale = _quantize_weight(np.asarray(conv.weight._data), out_axis=0)
    bias = conv.bias._data if getattr(conv, "bias", None) is not None \
        else None
    return Int8InferenceConv2D(q, scale, bias, stride=conv._stride,
                               padding=conv._padding,
                               dilation=conv._dilation,
                               groups=conv._groups)


def convert_to_int8_inference(model: Layer,
                              convert_conv: bool = True) -> Layer:
    """Swap every nn.Linear (and, by default, every NCHW nn.Conv2D) for
    its Int8Inference counterpart — the PTQ deploy step
    (post_training_quantization.py convert) over the vision zoo.  A bare
    Linear/Conv2D is converted and RETURNED (it cannot be swapped in
    place); use the return value."""
    from paddle_tpu.nn.layer.common import Linear
    from paddle_tpu.nn.layer.conv import Conv2D

    # exact-type matches only: tensor-parallel Linear subclasses
    # (Column/RowParallelLinear) carry sharding semantics (dist_attr,
    # gather/reshard behaviour) that a plain Int8InferenceLinear would
    # silently drop — they stay untouched
    def _convertible_linear(m):
        return type(m) is Linear

    def _convertible_conv(m):
        return (convert_conv and type(m) is Conv2D
                and m._data_format == "NCHW")

    if _convertible_linear(model):
        return _int8_of(model)
    if _convertible_conv(model):
        return _int8_of_conv(model)
    for name, child in list(model._sub_layers.items()):
        if _convertible_linear(child):
            model._sub_layers[name] = _int8_of(child)
        elif _convertible_conv(child):
            model._sub_layers[name] = _int8_of_conv(child)
        else:
            convert_to_int8_inference(child, convert_conv=convert_conv)
    return model
