"""Quantization (slim) tier — QAT fake-quant + post-training quantization.

Reference: python/paddle/fluid/contrib/slim/quantization/ —
  * quantization_pass.py fake_quantize_abs_max /
    fake_quantize_moving_average_abs_max / channel-wise variants (the op
    kernels live in operators/fake_quantize_op.cc);
  * imperative/qat.py ImperativeQuantAware — swaps Linear/Conv2D for
    quantized counterparts that fake-quant weights + activations;
  * post_training_quantization.py — calibrate abs-max over sample data,
    then store int8 weights + scales.

TPU notes: int8 matmul on the MXU is not exposed through jax today, so
the *execution* of quantized layers stays bf16/fp32 with
quantize→dequantize applied (exactly what the reference's fake-quant
training path computes); the artifacts (int8 weights + scales from PTQ)
are the deployment contract.  Gradients flow via the straight-through
estimator: ``x + stop_gradient(q(x) - x)`` — identity backward, quantized
forward, matching fake_quantize_op's grad kernel.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["fake_quantize_dequantize_abs_max",
           "fake_channel_wise_quantize_dequantize_abs_max",
           "MovingAverageAbsMaxObserver", "QuantizedLinear",
           "ImperativeQuantAware", "quant_post_weights", "dequant_weights"]


def _qmax(bits: int) -> float:
    return float(2 ** (bits - 1) - 1)


def fake_quantize_dequantize_abs_max(x, bits: int = 8, name=None):
    """operators/fake_quantize_op.cc FakeQuantizeDequantizeAbsMax: scale =
    max|x|; straight-through gradient."""
    qm = _qmax(bits)

    def _q(a):
        scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-8)
        q = jnp.round(a / scale * qm) / qm * scale
        return a + jax.lax.stop_gradient(q - a)
    return apply1(_q, x, name="fake_quant_dequant_abs_max")


def fake_channel_wise_quantize_dequantize_abs_max(x, bits: int = 8,
                                                  quant_axis: int = 0,
                                                  name=None):
    """Per-output-channel scales (fake_channel_wise_quantize_op) — the
    weight path of QAT conv/linear."""
    qm = _qmax(bits)

    def _q(a):
        axes = tuple(i for i in range(a.ndim) if i != quant_axis)
        scale = jnp.maximum(jnp.max(jnp.abs(a), axis=axes, keepdims=True),
                            1e-8)
        q = jnp.round(a / scale * qm) / qm * scale
        return a + jax.lax.stop_gradient(q - a)
    return apply1(_q, x, name="fake_channel_wise_quant")


class MovingAverageAbsMaxObserver:
    """fake_quantize_moving_average_abs_max state machine (rate 0.9) for
    activation scales."""

    def __init__(self, rate: float = 0.9):
        self.rate = rate
        self.scale: Optional[float] = None

    def update(self, x) -> float:
        cur = float(jnp.max(jnp.abs(
            x._data if isinstance(x, Tensor) else jnp.asarray(x))))
        self.scale = cur if self.scale is None else \
            self.rate * self.scale + (1 - self.rate) * cur
        return max(self.scale, 1e-8)

    def quantize(self, x, bits: int = 8):
        qm = _qmax(bits)
        scale = self.update(x)

        def _q(a):
            q = jnp.clip(jnp.round(a / scale * qm), -qm, qm) / qm * scale
            return a + jax.lax.stop_gradient(q - a)
        return apply1(_q, x, name="fake_quant_moving_avg")


class QuantizedLinear(Layer):
    """imperative/qat.py QuantizedLinear: fake-quant weight (channel-wise)
    and input activation (moving-average) around the dense matmul."""

    def __init__(self, linear, weight_bits: int = 8, activation_bits: int = 8):
        super().__init__()
        self.weight = linear.weight
        self.bias = getattr(linear, "bias", None)
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._observer = MovingAverageAbsMaxObserver()

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        xq = self._observer.quantize(x, self.activation_bits)
        wq = fake_channel_wise_quantize_dequantize_abs_max(
            self.weight, self.weight_bits, quant_axis=1)
        return F.linear(xq, wq, self.bias)


class ImperativeQuantAware:
    """imperative/qat.py ImperativeQuantAware.quantize: in-place module
    swap Linear→QuantizedLinear (the reference also covers Conv2D; conv
    follows the same recipe via fake_channel_wise on axis 0)."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def quantize(self, model: Layer) -> Layer:
        from paddle_tpu.nn.layer.common import Linear
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, Linear):
                model._sub_layers[name] = QuantizedLinear(
                    child, self.weight_bits, self.activation_bits)
            else:
                self.quantize(child)
        return model


# ---------------------------------------------------------------------------
# post-training (weight) quantization
# ---------------------------------------------------------------------------

def quant_post_weights(model: Layer, bits: int = 8) -> Dict[str, dict]:
    """post_training_quantization.py weight path: per-channel int8 weights
    + float scales for every Linear weight; returns the deployment dict
    {param_name: {"int": int8 array, "scale": [out] scales}}."""
    qm = _qmax(bits)
    out = {}
    for name, p in model.named_parameters():
        if p._data.ndim != 2 or not name.endswith("weight"):
            continue
        w = np.asarray(p._data, np.float32)
        scale = np.maximum(np.abs(w).max(axis=0), 1e-8)      # per out-col
        q = np.clip(np.round(w / scale * qm), -qm, qm).astype(np.int8)
        out[name] = {"int": q, "scale": (scale / qm).astype(np.float32)}
    return out


def dequant_weights(packed: Dict[str, dict]) -> Dict[str, np.ndarray]:
    return {n: d["int"].astype(np.float32) * d["scale"]
            for n, d in packed.items()}
