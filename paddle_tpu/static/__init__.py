"""Static-graph compatibility surface.

The reference's static mode (ProgramDesc + Executor, python/paddle/static/)
has no TPU-native analogue — jit capture *is* the static mode.  This module
keeps the API names alive: ``paddle.enable_static()`` flips a flag,
``static.InputSpec`` feeds paddle_tpu.jit.to_static, and Program/Executor
raise informative errors pointing at the jit path.
"""
from __future__ import annotations

import threading

__all__ = ["InputSpec", "enable_static", "disable_static"]

_state = threading.local()


def _in_static_mode() -> bool:
    return getattr(_state, "static", False)


def _enable_static():
    _state.static = True


def _disable_static():
    _state.static = False


def enable_static():
    _enable_static()


def disable_static():
    _disable_static()


class InputSpec:
    """Shape/dtype spec for jit capture (parity:
    paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype!r})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name)


class Program:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "ProgramDesc-style static graphs do not exist in paddle_tpu; "
            "use paddle_tpu.jit.to_static (XLA capture) instead")


class Executor:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "the C++ Executor does not exist in paddle_tpu; jit-compiled "
            "functions dispatch straight to XLA (see paddle_tpu.jit)")


from paddle_tpu.static import nn  # noqa: E402,F401
from paddle_tpu.static.nn import (  # noqa: E402,F401
    case, cond, switch_case, while_loop)
