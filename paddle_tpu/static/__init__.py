"""Static-graph compatibility surface.

The reference's static mode (ProgramDesc + Executor, python/paddle/static/)
has no TPU-native analogue — jit capture *is* the static mode.  This module
keeps the API names alive: ``paddle.enable_static()`` flips a flag,
``static.InputSpec`` feeds paddle_tpu.jit.to_static, and Program/Executor
raise informative errors pointing at the jit path.
"""
from __future__ import annotations

import threading

__all__ = ["InputSpec", "enable_static", "disable_static"]

_state = threading.local()


def _in_static_mode() -> bool:
    return getattr(_state, "static", False)


def _enable_static():
    _state.static = True


def _disable_static():
    _state.static = False


def enable_static():
    _enable_static()


def disable_static():
    _disable_static()


class InputSpec:
    """Shape/dtype spec for jit capture (parity:
    paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype!r})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name)


class Program:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "ProgramDesc-style static graphs do not exist in paddle_tpu; "
            "use paddle_tpu.jit.to_static (XLA capture) instead")


class Executor:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "the C++ Executor does not exist in paddle_tpu; jit-compiled "
            "functions dispatch straight to XLA (see paddle_tpu.jit)")


from paddle_tpu.static import nn  # noqa: E402,F401
from paddle_tpu.static.nn import (  # noqa: E402,F401
    case, cond, switch_case, while_loop)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Deploy-artifact export under the 2.0 static API name (reference:
    python/paddle/static/io.py save_inference_model -> Program pruning +
    serialization).  TPU-native: the model is a Layer whose jit capture
    IS the pruned program — ``fetch_vars`` must be the Layer (or carry
    ``.model``); ``feed_vars`` supply the InputSpecs.  Produces the same
    artifact as ``paddle_tpu.jit.save`` (StableHLO + params), loadable by
    ``paddle_tpu.jit.load`` / ``inference.create_predictor``."""
    from paddle_tpu import jit
    from paddle_tpu.nn.layer.layers import Layer
    layer = fetch_vars if isinstance(fetch_vars, Layer) else \
        getattr(fetch_vars, "model", None)
    if layer is None:
        raise TypeError(
            "save_inference_model(fetch_vars=...) must be the Layer to "
            "export (there is no Program to prune in paddle_tpu; the "
            "Layer's traced forward plays that role)")
    specs = list(feed_vars) if feed_vars is not None else None
    jit.save(layer, path_prefix, input_spec=specs)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Counterpart of save_inference_model: returns the TranslatedLayer
    (callable like the reference's (program, feeds, fetches) triple —
    call it with input Tensors to get the fetch outputs)."""
    from paddle_tpu import jit
    return jit.load(path_prefix)


__all__ += ["save_inference_model", "load_inference_model", "Program",
            "Executor"]
