"""Control-flow API surface — paddle.static.nn.{while_loop, cond, case,
switch_case} parity.

Reference: python/paddle/fluid/layers/control_flow.py (while_loop :1115,
cond :2197, case :2719, switch_case :3277) — block-building ops executed
by the interpreter's conditional/while op kernels.

TPU mapping — one API, two regimes (the same dual-regime rule as the
collectives):
  * **eager** (concrete Tensors): plain python control flow.  The tape
    records whichever branch/iterations actually ran, so backward works
    exactly like the reference's dygraph mode.
  * **in-trace** (inside jit/TrainStep capture, tracer-backed Tensors):
    lowers to ``lax.while_loop`` / ``lax.cond`` / ``lax.switch`` —
    compiler-friendly control flow with no unrolling, the XLA-native
    replacement for the reference's WhileOp/ConditionalBlockOp kernels.

Loop state must be a flat list/tuple of Tensors with loop-invariant
shapes/dtypes (the reference imposes the same via assign-to-same-var).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core import Tensor

__all__ = ["while_loop", "cond", "case", "switch_case"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_tracer(x) -> bool:
    return isinstance(_unwrap(x), jax.core.Tracer)


def _any_tracer(vals) -> bool:
    return any(_is_tracer(v) for v in jax.tree_util.tree_leaves(
        [_unwrap(v) for v in vals]))


def _wrap_list(arrs, like):
    out = []
    for a, l in zip(arrs, like):
        out.append(Tensor(a) if isinstance(l, Tensor) else a)
    return out


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: Optional[str] = None) -> List:
    """control_flow.py:1115.  ``cond(*vars) -> scalar bool``,
    ``body(*vars) -> new vars`` (same structure/shapes)."""
    if not callable(cond) or not callable(body):
        raise TypeError("cond and body must be callable")
    loop_vars = list(loop_vars)
    if not loop_vars:
        raise ValueError("loop_vars cannot be empty")

    if not _any_tracer(loop_vars) and not _is_tracer(cond(*loop_vars)):
        # eager: python loop; the tape sees the executed iterations
        vals = loop_vars
        while bool(_unwrap(cond(*vals))):
            out = body(*vals)
            vals = list(out) if isinstance(out, (list, tuple)) else [out]
            if len(vals) != len(loop_vars):
                raise ValueError(
                    f"body returned {len(vals)} vars, expected "
                    f"{len(loop_vars)}")
        return vals

    # in-trace: lax.while_loop over raw arrays
    init = tuple(_unwrap(v) for v in loop_vars)

    def _cond(c):
        return jnp.asarray(_unwrap(cond(*_wrap_list(c, loop_vars)))) \
            .reshape(())

    def _body(c):
        out = body(*_wrap_list(c, loop_vars))
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(_unwrap(o) for o in out)

    final = lax.while_loop(_cond, _body, init)
    return _wrap_list(final, loop_vars)


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name: Optional[str] = None):
    """control_flow.py:2197.  Both branches must return the same
    structure (the reference errors likewise at runtime)."""
    if _is_tracer(pred):
        tf = true_fn or (lambda: None)
        ff = false_fn or (lambda: None)

        def _run(fn):
            def inner(_):
                out = fn()
                return jax.tree_util.tree_map(_unwrap, out)
            return inner
        out = lax.cond(jnp.asarray(_unwrap(pred)).reshape(()).astype(bool),
                       _run(tf), _run(ff), operand=None)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a) if a is not None else None, out)
    taken = true_fn if bool(_unwrap(pred)) else false_fn
    return taken() if taken is not None else None


def case(pred_fn_pairs: Sequence[Tuple], default: Optional[Callable] = None,
         name: Optional[str] = None):
    """control_flow.py:2719 — first true predicate wins; eager and
    in-trace (chained lax.cond) regimes."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs cannot be empty")
    for p, f in pred_fn_pairs:
        if not callable(f):
            raise TypeError("branch fns must be callable")
    if not any(_is_tracer(p) for p, _ in pred_fn_pairs):
        for p, f in pred_fn_pairs:
            if bool(_unwrap(p)):
                return f()
        if default is None:
            raise ValueError("no predicate true and no default given")
        return default()
    # in-trace: fold into nested lax.cond, last-else = default (or the
    # last branch, matching the reference's default=None behaviour)
    if default is None:
        default = pred_fn_pairs[-1][1]
        pred_fn_pairs = pred_fn_pairs[:-1]

    def build(i):
        if i == len(pred_fn_pairs):
            return lambda: jax.tree_util.tree_map(_unwrap, default())
        p, f = pred_fn_pairs[i]
        nxt = build(i + 1)
        return lambda: lax.cond(
            jnp.asarray(_unwrap(p)).reshape(()).astype(bool),
            lambda _: jax.tree_util.tree_map(_unwrap, f()),
            lambda _: nxt(), operand=None)
    out = build(0)()
    return jax.tree_util.tree_map(Tensor, out)


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name: Optional[str] = None):
    """control_flow.py:3277.  ``branch_fns``: dict {int: fn} or sequence of
    (int, fn) / bare fns.  Out-of-range indices take ``default`` (or the
    max-index branch, per the reference)."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = [(i, f) if callable(f) else tuple(f)
                 for i, f in enumerate(branch_fns)]
        pairs = [(int(k), f) for k, f in pairs]
    keys = [k for k, _ in pairs]
    if len(set(keys)) != len(keys):
        raise ValueError("duplicate branch indices")
    if default is None:
        default = pairs[-1][1]
    if not _is_tracer(branch_index):
        k = int(_unwrap(branch_index))
        for key, f in pairs:
            if key == k:
                return f()
        return default()
    # in-trace: map sparse keys onto a dense lax.switch table + default slot
    key_arr = jnp.asarray(keys)
    idx = jnp.asarray(_unwrap(branch_index)).reshape(()).astype(jnp.int32)
    matches = (key_arr == idx)
    dense = jnp.where(matches.any(), jnp.argmax(matches), len(pairs))
    fns = [(lambda f=f: jax.tree_util.tree_map(_unwrap, f()))
           for _, f in pairs]
    fns.append(lambda: jax.tree_util.tree_map(_unwrap, default()))
    out = lax.switch(dense, fns)
    return jax.tree_util.tree_map(Tensor, out)
