"""paddle_tpu.tensor — the tensor method library.

Parity target: python/paddle/tensor/ (~9k LoC in the reference).  Every public
function is exposed both as ``paddle_tpu.<fn>`` and as a ``Tensor`` method,
mirroring the reference's monkey-patching of VarBase
(python/paddle/fluid/dygraph/varbase_patch_methods.py + tensor/__init__.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply, apply1, convert_dtype

from paddle_tpu.tensor import creation, linalg, logic, manipulation, math
from paddle_tpu.tensor import random, search, sequence, stat
from paddle_tpu.tensor.creation import *  # noqa: F401,F403
from paddle_tpu.tensor.linalg import *  # noqa: F401,F403
from paddle_tpu.tensor.logic import *  # noqa: F401,F403
from paddle_tpu.tensor.manipulation import *  # noqa: F401,F403
from paddle_tpu.tensor.math import *  # noqa: F401,F403
from paddle_tpu.tensor.random import *  # noqa: F401,F403
from paddle_tpu.tensor.search import *  # noqa: F401,F403
from paddle_tpu.tensor.sequence import *  # noqa: F401,F403
from paddle_tpu.tensor.stat import (mean, std, var, median, nanmedian,  # noqa: F401
                                    quantile, nanquantile)


def einsum(equation, *operands):
    """paddle.einsum parity → jnp.einsum (MXU-friendly contraction)."""
    return apply1(lambda *arrs: jnp.einsum(equation, *arrs), *operands,
                  name="einsum")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """paddle.histogramdd parity → jnp.histogramdd."""
    def _h(a, *rest):
        w = rest[0] if rest else None
        hist, edges = jnp.histogramdd(a, bins=bins, range=ranges,
                                      density=density, weights=w)
        return (hist,) + tuple(edges)
    args = (x,) if weights is None else (x, weights)
    outs = apply(_h, *args, name="histogramdd")
    return outs[0], list(outs[1:])


# ---------------------------------------------------------------------------
# operator overloads + method patching
# ---------------------------------------------------------------------------

def _coerce(other):
    return other


def _patch_tensor_methods():
    T = Tensor

    # arithmetic dunders
    T.__add__ = lambda s, o: math.add(s, _coerce(o))
    T.__radd__ = lambda s, o: math.add(s, _coerce(o))
    T.__sub__ = lambda s, o: math.subtract(s, _coerce(o))
    T.__rsub__ = lambda s, o: apply1(lambda a: jnp.subtract(o, a), s, name="rsub")
    T.__mul__ = lambda s, o: math.multiply(s, _coerce(o))
    T.__rmul__ = lambda s, o: math.multiply(s, _coerce(o))
    T.__truediv__ = lambda s, o: math.divide(s, _coerce(o))
    T.__rtruediv__ = lambda s, o: apply1(lambda a: jnp.divide(o, a), s,
                                         name="rdiv")
    T.__floordiv__ = lambda s, o: math.floor_divide(s, _coerce(o))
    T.__mod__ = lambda s, o: math.remainder(s, _coerce(o))
    T.__pow__ = lambda s, o: math.pow(s, _coerce(o))
    T.__rpow__ = lambda s, o: apply1(lambda a: jnp.power(o, a), s, name="rpow")
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: apply1(lambda a: jnp.matmul(o, a), s,
                                        name="rmatmul")

    # comparisons
    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)
    T.__invert__ = lambda s: logic.logical_not(s)
    T.__and__ = lambda s, o: logic.bitwise_and(s, o)
    T.__or__ = lambda s, o: logic.bitwise_or(s, o)
    T.__xor__ = lambda s, o: logic.bitwise_xor(s, o)

    # indexing
    def _getitem(s, idx):
        def unwrap(i):
            if isinstance(i, Tensor):
                return i._data
            if isinstance(i, tuple):
                return tuple(unwrap(j) for j in i)
            return i
        idx = unwrap(idx)
        return apply1(lambda a: a[idx], s, name="getitem")

    def _setitem(s, idx, value):
        def unwrap(i):
            if isinstance(i, Tensor):
                return i._data
            if isinstance(i, tuple):
                return tuple(unwrap(j) for j in i)
            return i
        idx = unwrap(idx)
        v = value._data if isinstance(value, Tensor) else value
        s._data = s._data.at[idx].set(v)

    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    # attach function namespaces as methods
    mods = [creation, linalg, logic, manipulation, math, search, stat, random]
    skip = {"to_tensor", "as_tensor", "zeros", "ones", "full", "empty",
            "arange", "linspace", "logspace", "eye", "meshgrid", "rand",
            "randn", "randint", "uniform", "normal", "randperm", "seed",
            "create_parameter", "create_tensor", "is_tensor",
            "standard_normal", "poisson", "get_rng_state", "set_rng_state"}
    for mod in mods:
        for fname in getattr(mod, "__all__", []):
            if fname in skip or hasattr(T, fname):
                continue
            fn = getattr(mod, fname, None)
            if callable(fn):
                setattr(T, fname, fn)

    # common aliases / extras
    T.astype = lambda s, dtype: manipulation.cast(s, dtype)
    T.cast = T.astype
    T.dim = lambda s: s.ndim
    T.rank = lambda s: Tensor(np.int64(s.ndim))
    T.mean = stat.mean
    T.std = stat.std
    T.var = stat.var
    T.reshape = manipulation.reshape
    T.pow = math.pow
    T.abs = math.abs
    T.sum = math.sum
    T.max = math.max
    T.min = math.min
    T.matmul = linalg.matmul
    T.mm = linalg.mm
    T.norm = linalg.norm
    T.scale = math.scale
    T.exp = math.exp
    T.log = math.log
    T.sqrt = math.sqrt
    T.tanh = math.tanh
    T.sigmoid = math.sigmoid
    T.unique = manipulation.unique
    T.topk = search.topk
    T.uniform_ = random.uniform_
    T.normal_ = random.normal_
    T.exponential_ = random.exponential_

    def _add_(s, o):
        s._data = s._data + (o._data if isinstance(o, Tensor) else o)
        return s

    def _scale_(s, scale=1.0, bias=0.0):
        s._data = s._data * scale + bias
        return s

    def _subtract_(s, o):
        s._data = s._data - (o._data if isinstance(o, Tensor) else o)
        return s

    def _clip_(s, min=None, max=None):
        s._data = jnp.clip(s._data, min, max)
        return s

    T.add_ = _add_
    T.scale_ = _scale_
    T.subtract_ = _subtract_
    T.clip_ = _clip_


_patch_tensor_methods()


def add_n(inputs, name=None):
    """operators/sum_op parity."""
    if isinstance(inputs, Tensor):
        return inputs
    return apply1(lambda *arrs: sum_arrays(arrs), *inputs, name="add_n")


def sum_arrays(arrs):
    out = arrs[0]
    for a in arrs[1:]:
        out = out + a
    return out
