"""Statistics ops (parity: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile",
           "nanquantile", "numel"]


def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), x,
                  name="mean")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.std(a, axis=ax, ddof=1 if unbiased else 0,
                                    keepdims=keepdim), x, name="std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.var(a, axis=ax, ddof=1 if unbiased else 0,
                                    keepdims=keepdim), x, name="var")


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x,
                  name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.nanmedian(a, axis=ax, keepdims=keepdim), x,
                  name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.quantile(a, jnp.asarray(q), axis=ax,
                                         keepdims=keepdim), x, name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=ax,
                                            keepdims=keepdim), x,
                  name="nanquantile")


def numel(x, name=None):
    import numpy as np
    return Tensor(np.int64(x.size))
