"""Comparison + logical ops (parity: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply1

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "is_empty",
    "is_tensor",
]


def _cmp(jfn, name):
    def op(x, y, name=None):
        return apply1(jfn, x, y, name=name)
    op.__name__ = name
    return op


equal = _cmp(lambda a, b: jnp.equal(a, b), "equal")
not_equal = _cmp(jnp.not_equal, "not_equal")
greater_than = _cmp(jnp.greater, "greater_than")
greater_equal = _cmp(jnp.greater_equal, "greater_equal")
less_than = _cmp(jnp.less, "less_than")
less_equal = _cmp(jnp.less_equal, "less_equal")
logical_and = _cmp(jnp.logical_and, "logical_and")
logical_or = _cmp(jnp.logical_or, "logical_or")
logical_xor = _cmp(jnp.logical_xor, "logical_xor")
bitwise_and = _cmp(jnp.bitwise_and, "bitwise_and")
bitwise_or = _cmp(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _cmp(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, out=None, name=None):
    return apply1(jnp.logical_not, x, name="logical_not")


def bitwise_not(x, out=None, name=None):
    return apply1(jnp.bitwise_not, x, name="bitwise_not")


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
