"""Ragged/sequence subsystem — the TPU-native replacement for LoDTensor.

The reference makes raggedness a *tensor property*: LoD offset tables ride
along every tensor (paddle/fluid/framework/lod_tensor.h:114) and ~20
operators in paddle/fluid/operators/sequence_ops/ consume them
(sequence_pool_op.h, sequence_pad_op.h, sequence_mask_op.h,
sequence_softmax_op.h, sequence_reverse_op.h, sequence_expand_op.h, ...),
plus the fused sparse path fused_embedding_seq_pool_op.h.

XLA wants static shapes, so here raggedness is *explicit data*, not a
hidden tensor attribute.  Two interchangeable encodings:

  * padded-dense  — ``(data [B, maxlen, ...], lengths [B])``.  Canonical
    on-device form: every op is a masked dense op that the MXU/VPU can
    tile, and ``maxlen`` is a static shape so everything jits.
  * flat-segmented — ``(values [total, ...], segment_ids [total])``.  For
    segment reductions / embedding-bag, via ``jax.ops.segment_*`` (which
    lower to one-hot matmuls or sorted scatters XLA handles well).

Conversions: :func:`sequence_pad` / :func:`sequence_unpad` /
:func:`lengths_to_segment_ids`.  ``sequence_unpad`` has a data-dependent
output shape and is therefore eager-only; inside ``jit`` stay in padded
form (that is the point of the design).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad",
    "lengths_to_segment_ids", "segment_ids_to_lengths",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "segment_softmax",
    "sequence_pool", "sequence_softmax", "sequence_reverse",
    "sequence_first_step", "sequence_last_step", "sequence_concat",
    "sequence_expand_as", "sequence_enumerate",
    "embedding_bag",
]


def _as_int(a):
    return a.astype(jnp.int32)


def _static_int(v, name):
    if isinstance(v, Tensor):
        v = int(v.numpy())
    if v is None:
        raise ValueError(f"{name} must be a static python int on TPU "
                         "(shapes under jit cannot be data-dependent)")
    return int(v)


# ---------------------------------------------------------------------------
# masks + encoding conversions
# ---------------------------------------------------------------------------

def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """reference: operators/sequence_ops/sequence_mask_op.h (MaskFunctor):
    mask[i, j] = j < x[i].  ``maxlen`` must be static under jit; eagerly it
    defaults to ``max(x)``."""
    if maxlen is None:
        maxlen = int(np.max(np.asarray(
            x.numpy() if isinstance(x, Tensor) else x)))
    maxlen = _static_int(maxlen, "maxlen")

    def _mask(lengths):
        pos = jnp.arange(maxlen, dtype=jnp.int32)
        return (pos[None, :] < _as_int(lengths)[..., None]).astype(dtype)
    return apply1(_mask, x, nondiff=(0,), name="sequence_mask")


def lengths_to_segment_ids(lengths, name=None):
    """[3, 1, 2] -> [0, 0, 0, 1, 2, 2] (flat, eager) — the LoD offset table
    → segment-id bridge.  Eager-only: output length is sum(lengths)."""
    lens = np.asarray(lengths.numpy() if isinstance(lengths, Tensor)
                      else lengths).astype(np.int64)
    return Tensor(jnp.asarray(np.repeat(np.arange(lens.size), lens)),
                  stop_gradient=True)


def segment_ids_to_lengths(segment_ids, num_segments, name=None):
    num_segments = _static_int(num_segments, "num_segments")

    def _run(sids):
        return jax.ops.segment_sum(jnp.ones_like(sids, dtype=jnp.int64),
                                   _as_int(sids), num_segments=num_segments)
    return apply1(_run, segment_ids, nondiff=(0,), name="segment_ids_to_lengths")


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """flat values [total, ...] + lengths [B] -> (padded [B, maxlen, ...],
    lengths).  reference: operators/sequence_ops/sequence_pad_op.h, with the
    LoD argument made explicit.  Eager-friendly scatter; also jittable since
    ``total`` and ``maxlen`` are static at trace time."""
    if maxlen is None:
        maxlen = int(np.max(np.asarray(
            lengths.numpy() if isinstance(lengths, Tensor) else lengths)))
    maxlen = _static_int(maxlen, "maxlen")

    def _pad(values, lens):
        lens = _as_int(lens)
        b = lens.shape[0]
        starts = jnp.cumsum(lens) - lens                       # [B]
        # row/col of every flat element in the padded output
        seg = jnp.repeat(jnp.arange(b, dtype=jnp.int32), lens,
                         total_repeat_length=values.shape[0])
        pos = jnp.arange(values.shape[0], dtype=jnp.int32) - starts[seg]
        out = jnp.full((b, maxlen) + values.shape[1:], pad_value,
                       dtype=values.dtype)
        return out.at[seg, pos].set(values)
    padded = apply1(_pad, x, lengths, nondiff=(1,), name="sequence_pad")
    return padded, lengths


def sequence_unpad(x, length, name=None):
    """padded [B, maxlen, ...] + lengths [B] -> flat [total, ...]
    (reference: operators/sequence_ops/sequence_unpad_op.h).  Eager-only:
    ``total`` is data-dependent."""
    lens = np.asarray(length.numpy() if isinstance(length, Tensor)
                      else length).astype(np.int64)
    total = int(lens.sum())
    seg = np.repeat(np.arange(lens.size), lens)
    starts = np.cumsum(lens) - lens
    pos = np.arange(total) - starts[seg]

    def _unpad(padded):
        return padded[jnp.asarray(seg), jnp.asarray(pos)]
    return apply1(_unpad, x, name="sequence_unpad")


# ---------------------------------------------------------------------------
# segment reductions (flat-segmented encoding)
# ---------------------------------------------------------------------------

def _infer_num_segments(segment_ids, num_segments):
    if num_segments is not None:
        return num_segments
    return int(np.max(np.asarray(
        segment_ids.numpy() if isinstance(segment_ids, Tensor)
        else segment_ids))) + 1


def _segment_reduce(kind, data, segment_ids, num_segments, name):
    num_segments = _static_int(num_segments, "num_segments")
    ops = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}

    def _run(vals, sids):
        sids = _as_int(sids)
        if kind == "mean":
            s = jax.ops.segment_sum(vals, sids, num_segments=num_segments)
            n = jax.ops.segment_sum(jnp.ones((vals.shape[0],), vals.dtype),
                                    sids, num_segments=num_segments)
            shape = (num_segments,) + (1,) * (vals.ndim - 1)
            return s / jnp.maximum(n, 1.0).reshape(shape)
        out = ops[kind](vals, sids, num_segments=num_segments)
        if kind in ("max", "min"):
            # empty segments come back ±inf; zero them like the reference's
            # sequence_pool (sequence_pool_op.h pads empty seqs with 0)
            n = jax.ops.segment_sum(jnp.ones((vals.shape[0],)), sids,
                                    num_segments=num_segments)
            shape = (num_segments,) + (1,) * (vals.ndim - 1)
            out = jnp.where(n.reshape(shape) > 0, out,
                            jnp.zeros_like(out))
        return out
    return apply1(_run, data, segment_ids, nondiff=(1,), name=name)


def segment_sum(data, segment_ids, num_segments=None, name=None):
    """reference role: operators/segment_pool_op (SUM) — flat values grouped
    by segment id, summed.  Differentiable in ``data``."""
    return _segment_reduce("sum", data, segment_ids,
                           _infer_num_segments(segment_ids, num_segments),
                           "segment_sum")


def segment_mean(data, segment_ids, num_segments=None, name=None):
    return _segment_reduce("mean", data, segment_ids,
                           _infer_num_segments(segment_ids, num_segments),
                           "segment_mean")


def segment_max(data, segment_ids, num_segments=None, name=None):
    return _segment_reduce("max", data, segment_ids,
                           _infer_num_segments(segment_ids, num_segments),
                           "segment_max")


def segment_min(data, segment_ids, num_segments=None, name=None):
    return _segment_reduce("min", data, segment_ids,
                           _infer_num_segments(segment_ids, num_segments),
                           "segment_min")


def segment_softmax(data, segment_ids, num_segments=None, name=None):
    """Softmax within each segment of a flat tensor (the sequence_softmax
    role — sequence_softmax_op.h — on the flat-segmented encoding)."""
    num_segments = _static_int(
        _infer_num_segments(segment_ids, num_segments), "num_segments")

    def _run(vals, sids):
        sids = _as_int(sids)
        mx = jax.ops.segment_max(vals, sids, num_segments=num_segments)
        mx = jnp.where(jnp.isfinite(mx), mx, 0.0)
        e = jnp.exp(vals - mx[sids])
        z = jax.ops.segment_sum(e, sids, num_segments=num_segments)
        return e / z[sids]
    return apply1(_run, data, segment_ids, nondiff=(1,),
                  name="segment_softmax")


# ---------------------------------------------------------------------------
# padded-dense sequence ops
# ---------------------------------------------------------------------------

def _time_mask(lens, t, extra_dims):
    m = jnp.arange(t, dtype=jnp.int32)[None, :] < _as_int(lens)[:, None]
    return m.reshape(m.shape + (1,) * extra_dims)


def sequence_pool(x, pool_type, lengths, pad_value=0.0, name=None):
    """Masked pooling over the time axis of padded [B, T, ...] input.
    pool_types: average/sum/sqrt/max/min/first/last
    (reference: operators/sequence_ops/sequence_pool_op.h + math/sequence_pooling.cc)."""
    pool_type = pool_type.lower()

    def _run(a, lens):
        lens = _as_int(lens)
        t = a.shape[1]
        m = _time_mask(lens, t, a.ndim - 2)
        empty = (lens == 0).reshape((-1,) + (1,) * (a.ndim - 2))
        if pool_type in ("average", "mean", "sum", "sqrt"):
            s = jnp.sum(jnp.where(m, a, 0.0), axis=1)
            if pool_type == "sum":
                out = s
            else:
                denom = jnp.maximum(lens, 1).astype(a.dtype)
                denom = denom.reshape((-1,) + (1,) * (a.ndim - 2))
                out = s / (jnp.sqrt(denom) if pool_type == "sqrt" else denom)
        elif pool_type == "max":
            out = jnp.max(jnp.where(m, a, -jnp.inf), axis=1)
            out = jnp.where(empty, 0.0, out)
        elif pool_type == "min":
            out = jnp.min(jnp.where(m, a, jnp.inf), axis=1)
            out = jnp.where(empty, 0.0, out)
        elif pool_type == "first":
            out = a[:, 0]
        elif pool_type == "last":
            idx = jnp.maximum(lens - 1, 0)
            out = jnp.take_along_axis(
                a, idx.reshape((-1, 1) + (1,) * (a.ndim - 2)), axis=1
            ).squeeze(1)
        else:
            raise ValueError(f"unknown pool_type {pool_type}")
        if pool_type in ("first", "last"):
            out = jnp.where(empty, pad_value, out)
        elif pad_value:
            out = jnp.where(empty, pad_value, out)
        return out
    return apply1(_run, x, lengths, nondiff=(1,), name="sequence_pool")


def sequence_first_step(x, lengths, name=None):
    return sequence_pool(x, "first", lengths)


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, "last", lengths)


def sequence_softmax(x, lengths, name=None):
    """Masked softmax along time for padded [B, T] / [B, T, ...] input
    (reference: operators/sequence_ops/sequence_softmax_op.h)."""
    def _run(a, lens):
        m = _time_mask(lens, a.shape[1], a.ndim - 2)
        z = jnp.where(m, a, -jnp.inf)
        z = z - jnp.max(jnp.where(m, a, -jnp.inf), axis=1, keepdims=True)
        e = jnp.where(m, jnp.exp(z), 0.0)
        denom = jnp.sum(e, axis=1, keepdims=True)
        return e / jnp.maximum(denom, 1e-30)
    return apply1(_run, x, lengths, nondiff=(1,), name="sequence_softmax")


def sequence_reverse(x, lengths, name=None):
    """Reverse each row's first lengths[i] steps, padding stays in place
    (reference: operators/sequence_ops/sequence_reverse_op.h)."""
    def _run(a, lens):
        lens = _as_int(lens)
        t = a.shape[1]
        pos = jnp.arange(t, dtype=jnp.int32)[None, :]
        src = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
        return jnp.take_along_axis(
            a, src.reshape(src.shape + (1,) * (a.ndim - 2)), axis=1)
    return apply1(_run, x, lengths, nondiff=(1,), name="sequence_reverse")


def sequence_concat(inputs, lengths_list, name=None):
    """Concatenate sequences row-wise in time: row i of the output is
    x1[i,:l1[i]] ++ x2[i,:l2[i]] ++ ...  (reference:
    operators/sequence_ops/sequence_concat_op.h).  Returns (padded, lengths)."""
    lens_np = [np.asarray(l.numpy() if isinstance(l, Tensor) else l)
               .astype(np.int64) for l in lengths_list]
    total = sum(lens_np)
    maxlen = int(total.max())

    def _run(*arrs):
        n = len(inputs)
        xs, lens = arrs[:n], [_as_int(l) for l in arrs[n:]]
        b = xs[0].shape[0]
        # one scratch column at index `maxlen` absorbs masked-out writes
        out = jnp.zeros((b, maxlen + 1) + xs[0].shape[2:], xs[0].dtype)
        offset = jnp.zeros((b,), jnp.int32)
        for a, l in zip(xs, lens):
            t = a.shape[1]
            pos = jnp.arange(t, dtype=jnp.int32)[None, :]
            valid = pos < l[:, None]
            dst = jnp.where(valid, offset[:, None] + pos, maxlen)
            rows = jnp.broadcast_to(
                jnp.arange(b, dtype=jnp.int32)[:, None], dst.shape)
            out = out.at[rows, dst].set(
                jnp.where(valid.reshape(valid.shape + (1,) * (a.ndim - 2)),
                          a, out[rows, dst]))
            offset = offset + l
        return out[:, :maxlen]
    padded = apply1(_run, *inputs, *lengths_list,
                    nondiff=tuple(range(len(inputs),
                                        len(inputs) + len(lengths_list))),
                    name="sequence_concat")
    return padded, Tensor(jnp.asarray(total), stop_gradient=True)


def sequence_expand_as(x, lengths, name=None):
    """Expand row i of x [B, ...] to lengths[i] flat copies — the
    sequence_expand_as_op.h role on the flat-segmented encoding.  Eager-only
    output length."""
    seg = lengths_to_segment_ids(lengths)

    def _run(a, sids):
        return jnp.take(a, _as_int(sids), axis=0)
    return apply1(_run, x, seg, nondiff=(1,), name="sequence_expand_as")


def sequence_enumerate(x, win_size, pad_value=0, lengths=None, name=None):
    """All win_size-grams per row of padded int ids [B, T] -> [B, T, win]
    (reference: operators/sequence_ops/sequence_enumerate_op.h), with
    positions past the row's length (or the tensor edge) set to pad_value."""
    win_size = int(win_size)

    def _run(ids, *rest):
        t = ids.shape[1]
        pos = jnp.arange(t, dtype=jnp.int32)[None, :, None] + \
            jnp.arange(win_size, dtype=jnp.int32)[None, None, :]
        limit = (rest[0].astype(jnp.int32)[:, None, None] if rest
                 else jnp.full((ids.shape[0], 1, 1), t, jnp.int32))
        valid = pos < jnp.minimum(limit, t)
        gathered = jnp.take_along_axis(
            ids[:, :, None], jnp.clip(pos, 0, t - 1), axis=1)
        return jnp.where(valid, gathered, pad_value)
    args = (x,) if lengths is None else (x, lengths)
    return apply1(_run, *args, nondiff=tuple(range(len(args))),
                  name="sequence_enumerate")


# ---------------------------------------------------------------------------
# embedding-bag (the fused_embedding_seq_pool role)
# ---------------------------------------------------------------------------

def embedding_bag(input, weight, lengths=None, mode="sum", padding_idx=None,
                  name=None):
    """Lookup + pooled reduction in one fused op — the role of
    operators/fused/fused_embedding_seq_pool_op.h (lookup + sequence_pool
    without materialising the [total, D] intermediate twice).

    Padded form: ``input`` [B, T] int ids + ``lengths`` [B] -> [B, D].
    Flat form  : ``input`` [total] ids with ``lengths`` as segment_ids of
    the same length -> [num_segments, D].

    On TPU, XLA fuses gather→masked-sum into a single pass over HBM; the
    sparse-gradient side of the reference op maps to the embedding-table
    subsystem (paddle_tpu.distributed.ps), not SelectedRows.
    """
    mode = mode.lower()
    if mode not in ("sum", "mean", "max"):
        raise ValueError(f"embedding_bag mode must be sum/mean/max, "
                         f"got {mode!r}")
    if input.ndim == 2:
        if lengths is None:
            lens = np.full((int(input.shape[0]),), int(input.shape[1]),
                           np.int64)
            lengths = Tensor(jnp.asarray(lens), stop_gradient=True)

        def _run(ids, w, lens):
            ids = _as_int(ids)
            e = jnp.take(w, ids, axis=0)                     # [B, T, D]
            m = _time_mask(lens, ids.shape[1], 1)
            if padding_idx is not None:
                m = m & (ids != padding_idx)[..., None]
            if mode == "max":
                out = jnp.max(jnp.where(m, e, -jnp.inf), axis=1)
                return jnp.where(jnp.isfinite(out), out, 0.0)
            s = jnp.sum(jnp.where(m, e, 0.0), axis=1)
            if mode == "sum":
                return s
            n = jnp.sum(m.astype(e.dtype), axis=1)
            return s / jnp.maximum(n, 1.0)
        return apply1(_run, input, weight, lengths, nondiff=(0, 2),
                      name="embedding_bag")
    # flat-segmented
    if lengths is None:
        raise ValueError("flat embedding_bag needs segment_ids in `lengths`")
    emb = apply1(lambda ids, w: jnp.take(w, _as_int(ids), axis=0),
                 input, weight, nondiff=(0,), name="embedding_bag.lookup")
    red = {"sum": segment_sum, "mean": segment_mean, "max": segment_max}[mode]
    return red(emb, lengths)
