"""Linear algebra ops (parity: python/paddle/tensor/linalg.py).

matmul maps straight onto the MXU via jnp; decompositions use
jax.numpy.linalg / jax.scipy.linalg (XLA custom calls).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import Tensor, apply, apply1

__all__ = [
    "matmul", "bmm", "mm", "mv", "norm", "dist", "cond", "cholesky",
    "cholesky_solve", "inverse", "det", "slogdet", "svd", "qr", "eig",
    "eigh", "eigvals", "eigvalsh", "matrix_power", "matrix_rank", "multi_dot",
    "pinv", "solve", "triangular_solve", "lstsq", "lu", "corrcoef", "cov",
    "histogram", "bincount", "mode",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def _matmul(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply1(_matmul, x, y, name="matmul")


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return apply1(jnp.matmul, x, y, name="bmm")


def mv(x, vec, name=None):
    return apply1(jnp.matmul, x, vec, name="mv")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def _norm(a):
        if axis is None and p == "fro":
            return jnp.sqrt(jnp.sum(a * a))
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=p, keepdims=keepdim)
        if isinstance(axis, (list, tuple)):
            return jnp.linalg.norm(a, ord="fro" if p == "fro" else p,
                                   axis=tuple(axis), keepdims=keepdim)
        if p == "fro":
            return jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    return apply1(_norm, x, name="norm")


def dist(x, y, p=2, name=None):
    def _dist(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply1(_dist, x, y, name="dist")


def cond(x, p=None, name=None):
    return apply1(lambda a: jnp.linalg.cond(a, p=p), x, name="cond")


def cholesky(x, upper=False, name=None):
    def _chol(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply1(_chol, x, name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def _cs(b, l):
        if upper:
            l = jnp.swapaxes(l, -1, -2)
        z = jax.scipy.linalg.solve_triangular(l, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(l, -1, -2), z, lower=False)
    return apply1(_cs, x, y, name="cholesky_solve")


def inverse(x, name=None):
    return apply1(jnp.linalg.inv, x, name="inverse")


def det(x, name=None):
    return apply1(jnp.linalg.det, x, name="det")


def slogdet(x, name=None):
    def _slogdet(a):
        s, l = jnp.linalg.slogdet(a)
        return jnp.stack([s, l])
    return apply1(_slogdet, x, name="slogdet")


def svd(x, full_matrices=False, name=None):
    outs = apply(lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
                 x, name="svd")
    return tuple(outs)


def qr(x, mode="reduced", name=None):
    outs = apply(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x, name="qr")
    return tuple(outs) if mode != "r" else outs[0]


def eig(x, name=None):
    import numpy as np
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    outs = apply(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x, name="eigh")
    return tuple(outs)


def eigvals(x, name=None):
    import numpy as np
    return Tensor(np.linalg.eigvals(np.asarray(x._data)))


def eigvalsh(x, UPLO="L", name=None):
    return apply1(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x,
                  name="eigvalsh")


def matrix_power(x, n, name=None):
    return apply1(lambda a: jnp.linalg.matrix_power(a, n), x,
                  name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply1(lambda a: jnp.linalg.matrix_rank(a, tol=tol), x,
                  name="matrix_rank")


def multi_dot(x, name=None):
    return apply1(lambda *arrs: jnp.linalg.multi_dot(arrs), *x,
                  name="multi_dot")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply1(lambda a: jnp.linalg.pinv(a, rtol=rcond), x, name="pinv")


def solve(x, y, name=None):
    return apply1(jnp.linalg.solve, x, y, name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    def _ts(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply1(_ts, x, y, name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    outs = apply(lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
                 x, y, name="lstsq")
    return tuple(outs)


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(x._data)
    outs = (Tensor(lu_), Tensor(piv.astype(jnp.int32)))
    if get_infos:
        outs = outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def corrcoef(x, rowvar=True, name=None):
    return apply1(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply1(lambda a: jnp.cov(a, rowvar=rowvar,
                                    ddof=1 if ddof else 0), x, name="cov")


def histogram(input, bins=100, min=0, max=0, name=None):
    def _hist(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        return jnp.histogram(a, bins=bins, range=(lo, hi))[0]
    return apply1(_hist, input, nondiff=(0,), name="histogram")


def bincount(x, weights=None, minlength=0, name=None):
    import numpy as np
    w = np.asarray(weights._data) if weights is not None else None
    return Tensor(np.bincount(np.asarray(x._data), weights=w,
                              minlength=minlength))


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    arr = np.asarray(x._data)
    from scipy import stats as _stats  # pragma: no cover
    raise NotImplementedError("mode: use paddle_tpu.tensor.search.kthvalue")
