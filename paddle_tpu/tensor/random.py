"""Random ops + global Generator.

Replaces the reference's ``framework::Generator`` (reference:
paddle/fluid/framework/generator.h:44 — global/per-device seeded Philox state)
with a stateful wrapper over JAX's counter-based PRNG: a global ``Generator``
holds a PRNGKey and splits per call.  Under ``to_static`` capture the key is
folded in as a constant; jitted training steps that need fresh randomness per
step should thread keys explicitly (see paddle_tpu.jit docs).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1, convert_dtype, get_default_dtype

__all__ = [
    "Generator", "seed", "get_rng_state", "set_rng_state", "default_generator",
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "poisson", "bernoulli", "multinomial", "randperm",
    "uniform_", "normal_", "exponential_",
]


class Generator:
    """Seeded PRNG stream (splitting JAX keys behind a stateful facade)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(int(seed))
        return self

    def initial_seed(self) -> int:
        return self._seed

    def split(self):
        with self._lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def get_state(self):
        return np.asarray(self._key)

    def set_state(self, state):
        self._key = jnp.asarray(state, dtype=jnp.uint32)


default_generator = Generator(0)


def seed(value: int):
    """paddle.seed parity — reseeds the global generator."""
    default_generator.manual_seed(value)
    return default_generator


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


def _key():
    return default_generator.split()


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]


def rand(shape, dtype=None, name=None) -> Tensor:
    dtype = convert_dtype(dtype) if dtype else convert_dtype(get_default_dtype())
    return Tensor(jax.random.uniform(_key(), _shape_list(shape), dtype=dtype))


def randn(shape, dtype=None, name=None) -> Tensor:
    dtype = convert_dtype(dtype) if dtype else convert_dtype(get_default_dtype())
    return Tensor(jax.random.normal(_key(), _shape_list(shape), dtype=dtype))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(), _shape_list(shape), low, high,
                                     dtype=convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    dtype = dtype or x.dtype
    return randint(low, high, shape=x.shape, dtype=dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    dtype = convert_dtype(dtype) if dtype else convert_dtype(get_default_dtype())
    k = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(jax.random.uniform(k, _shape_list(shape), dtype=dtype,
                                     minval=float(min), maxval=float(max)))


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else _shape_list(shape)
        z = jax.random.normal(_key(), out_shape,
                              dtype=convert_dtype(get_default_dtype()))
        return Tensor(m + s * z)
    out_shape = _shape_list(shape) if shape is not None else []
    z = jax.random.normal(_key(), out_shape,
                          dtype=convert_dtype(get_default_dtype()))
    return Tensor(mean + std * z)


def poisson(x, name=None) -> Tensor:
    return Tensor(jax.random.poisson(_key(), x._data).astype(x.dtype))


def bernoulli(x, name=None) -> Tensor:
    return Tensor(jax.random.bernoulli(_key(), x._data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    probs = x._data
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        out = jax.random.categorical(_key(), logits, axis=-1,
                                     shape=(num_samples,) + probs.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(_key(), probs.shape)
        out = jax.lax.top_k(logits + g, num_samples)[1]
    return Tensor(out.astype(jnp.int64))


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(_key(), n).astype(convert_dtype(dtype)))


# in-place variants (leaf mutation)
def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(_key(), x._data.shape, dtype=x._data.dtype,
                                 minval=float(min), maxval=float(max))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = mean + std * jax.random.normal(_key(), x._data.shape,
                                             dtype=x._data.dtype)
    return x


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(_key(), x._data.shape, dtype=x._data.dtype)
    x._data = -jnp.log(1.0 - u) / lam
    return x
