"""Search/sort ops (parity: python/paddle/tensor/search.py).

argsort/top_k lower to XLA sort/top-k; data-dependent-shape ops
(nonzero, masked_select) execute on host and are documented jit-incompatible
(the reference similarly syncs for these).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply, apply1

__all__ = [
    "argmax", "argmin", "argsort", "sort", "searchsorted", "topk", "where",
    "index_select", "nonzero", "masked_select", "kthvalue", "mode",
    "index_sample",
]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _argmax(a):
        out = jnp.argmax(a, axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.int64)
    return apply1(_argmax, x, nondiff=(0,), name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def _argmin(a):
        out = jnp.argmin(a, axis=axis)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(jnp.int64)
    return apply1(_argmin, x, nondiff=(0,), name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def _argsort(a):
        idx = jnp.argsort(a, axis=axis)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(jnp.int64)
    return apply1(_argsort, x, nondiff=(0,), name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def _sort(a):
        out = jnp.sort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out
    return apply1(_sort, x, name="sort")


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"

    def _ss(seq, v):
        out = jnp.searchsorted(seq, v, side=side)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply1(_ss, sorted_sequence, values, nondiff=(0, 1),
                  name="searchsorted")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else axis

    def _topk(a):
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(am, k)
        else:
            v, i = jax.lax.top_k(-am, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax).astype(jnp.int64)
    vals, idx = apply(_topk, x, name="topk")
    idx.stop_gradient = True
    return vals, idx


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply1(lambda c, a, b: jnp.where(c, a, b), condition, x, y,
                  nondiff=(0,), name="where")


def index_select(x, index, axis=0, name=None):
    from paddle_tpu.tensor.manipulation import gather
    return gather(x, index, axis=axis)


def index_sample(x, index, name=None):
    from paddle_tpu.tensor.manipulation import index_sample as _is
    return _is(x, index)


def nonzero(x, as_tuple=False):
    arr = np.asarray(x._data)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(i.astype(np.int64)) for i in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def masked_select(x, mask, name=None):
    arr = np.asarray(x._data)
    m = np.asarray(mask._data)
    return Tensor(arr[np.broadcast_to(m, arr.shape)])


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def _kth(a):
        s = jnp.sort(a, axis=axis)
        si = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        i = jnp.take(si, k - 1, axis=axis)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            i = jnp.expand_dims(i, axis)
        return v, i.astype(jnp.int64)
    v, i = apply(_kth, x, name="kthvalue")
    i.stop_gradient = True
    return v, i


def mode(x, axis=-1, keepdim=False, name=None):
    arr = np.asarray(x._data)
    arr_m = np.moveaxis(arr, axis, -1)
    flat = arr_m.reshape(-1, arr_m.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for j, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        v = uniq[np.argmax(counts)]
        vals[j] = v
        idxs[j] = np.nonzero(row == v)[0][-1]
    out_shape = arr_m.shape[:-1]
    v = vals.reshape(out_shape)
    i = idxs.reshape(out_shape)
    if keepdim:
        v = np.expand_dims(v, axis)
        i = np.expand_dims(i, axis)
    return Tensor(v), Tensor(i)
