"""Elementwise + reduction math ops.

Parity targets: python/paddle/tensor/math.py and the reference C++ op groups
operators/elementwise/, operators/reduce_ops/, activation_op.* — all of which
collapse to jnp/lax calls that XLA fuses on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1, apply, convert_dtype

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _unary(jfn, name):
    def op(x, name=None):
        return apply1(jfn, x, name=name)
    op.__name__ = name
    __all__.append(name)
    return op


def _binary(jfn, name):
    def op(x, y, name=None):
        return apply1(jfn, x, y, name=name)
    op.__name__ = name
    __all__.append(name)
    return op


# --- unary ------------------------------------------------------------------
exp = _unary(jnp.exp, "exp")
expm1 = _unary(jnp.expm1, "expm1")
log = _unary(jnp.log, "log")
log2 = _unary(jnp.log2, "log2")
log10 = _unary(jnp.log10, "log10")
log1p = _unary(jnp.log1p, "log1p")
sqrt = _unary(jnp.sqrt, "sqrt")
rsqrt = _unary(jax.lax.rsqrt, "rsqrt")
square = _unary(jnp.square, "square")
abs = _unary(jnp.abs, "abs")
sign = _unary(jnp.sign, "sign")
ceil = _unary(jnp.ceil, "ceil")
floor = _unary(jnp.floor, "floor")
round = _unary(jnp.round, "round")
trunc = _unary(jnp.trunc, "trunc")
frac = _unary(lambda x: x - jnp.trunc(x), "frac")
sin = _unary(jnp.sin, "sin")
cos = _unary(jnp.cos, "cos")
tan = _unary(jnp.tan, "tan")
asin = _unary(jnp.arcsin, "asin")
acos = _unary(jnp.arccos, "acos")
atan = _unary(jnp.arctan, "atan")
sinh = _unary(jnp.sinh, "sinh")
cosh = _unary(jnp.cosh, "cosh")
tanh = _unary(jnp.tanh, "tanh")
asinh = _unary(jnp.arcsinh, "asinh")
acosh = _unary(jnp.arccosh, "acosh")
atanh = _unary(jnp.arctanh, "atanh")
erf = _unary(jax.lax.erf, "erf")
erfinv = _unary(jax.lax.erf_inv, "erfinv")
reciprocal = _unary(lambda x: 1.0 / x, "reciprocal")
neg = _unary(jnp.negative, "neg")
digamma = _unary(jax.scipy.special.digamma, "digamma")
lgamma = _unary(jax.scipy.special.gammaln, "lgamma")
sigmoid = _unary(jax.nn.sigmoid, "sigmoid")
conj = _unary(jnp.conj, "conj")
angle = _unary(jnp.angle, "angle")
deg2rad = _unary(jnp.deg2rad, "deg2rad")
rad2deg = _unary(jnp.rad2deg, "rad2deg")

# --- binary -----------------------------------------------------------------
add = _binary(jnp.add, "add")
subtract = _binary(jnp.subtract, "subtract")
multiply = _binary(jnp.multiply, "multiply")
divide = _binary(jnp.divide, "divide")
floor_divide = _binary(jnp.floor_divide, "floor_divide")
remainder = _binary(jnp.remainder, "remainder")
mod = remainder
__all__.append("mod")
floor_mod = remainder
__all__.append("floor_mod")
pow_op = None


@_export
def pow(x, y, name=None):
    return apply1(jnp.power, x, y, name="pow")


maximum = _binary(jnp.maximum, "maximum")
minimum = _binary(jnp.minimum, "minimum")
fmax = _binary(jnp.fmax, "fmax")
fmin = _binary(jnp.fmin, "fmin")
atan2 = _binary(jnp.arctan2, "atan2")
hypot = _binary(lambda a, b: jnp.sqrt(a * a + b * b), "hypot")
logaddexp = _binary(jnp.logaddexp, "logaddexp")


@_export
def divide_no_nan(x, y, name=None):
    return apply1(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                  x, y, name="divide_no_nan")


@_export
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """operators/scale_op parity."""
    s = _unwrap(scale)

    def _scale(a, sv):
        out = a * sv + bias if bias_after_scale else (a + bias) * sv
        return out.astype(a.dtype) if not jnp.issubdtype(a.dtype, jnp.floating) else out
    out = apply1(_scale, x, s, name="scale")
    if act is not None:
        from paddle_tpu.nn import functional as F
        out = getattr(F, act)(out)
    return out


@_export
def clip(x, min=None, max=None, name=None):
    lo = _unwrap(min) if min is not None else None
    hi = _unwrap(max) if max is not None else None
    return apply1(lambda a: jnp.clip(a, lo, hi), x, name="clip")


@_export
def lerp(x, y, weight, name=None):
    return apply1(lambda a, b, w: a + w * (b - a), x, y, weight, name="lerp")


@_export
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply1(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y,
                  name="addmm")


@_export
def multiplex(inputs, index, name=None):
    def _mux(idx, *ins):
        stacked = jnp.stack(ins, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]
    return apply1(lambda idx, *ins: _mux(idx, *ins), index, *inputs,
                  nondiff=(0,), name="multiplex")


@_export
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply1(lambda a: scale_b * jnp.tanh(scale_a * a), x, name="stanh")


@_export
def kron(x, y, name=None):
    return apply1(jnp.kron, x, y, name="kron")


@_export
def inner(x, y, name=None):
    return apply1(jnp.inner, x, y, name="inner")


@_export
def outer(x, y, name=None):
    return apply1(jnp.outer, x, y, name="outer")


@_export
def cross(x, y, axis=None, name=None):
    ax = axis if axis is not None else -1
    return apply1(lambda a, b: jnp.cross(a, b, axis=ax), x, y, name="cross")


@_export
def dot(x, y, name=None):
    def _dot(a, b):
        if a.ndim == 1:
            return jnp.sum(a * b)
        return jnp.sum(a * b, axis=-1)
    return apply1(_dot, x, y, name="dot")


# --- reductions -------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(jfn, name):
    def op(x, axis=None, keepdim=False, name=None):
        ax = _norm_axis(axis)
        return apply1(lambda a: jfn(a, axis=ax, keepdims=keepdim), x, name=name)
    op.__name__ = name
    __all__.append(name)
    return op


sum = _reduce(jnp.sum, "sum")
prod = _reduce(jnp.prod, "prod")
max = _reduce(jnp.max, "max")
min = _reduce(jnp.min, "min")
amax = _reduce(jnp.max, "amax")
amin = _reduce(jnp.min, "amin")
mean = _reduce(jnp.mean, "mean")
nanmean = _reduce(jnp.nanmean, "nanmean")
nansum = _reduce(jnp.nansum, "nansum")
logsumexp_raw = None


@_export
def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jax.scipy.special.logsumexp(a, axis=ax,
                                                        keepdims=keepdim),
                  x, name="logsumexp")


@_export
def all(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x, name="all")


@_export
def any(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x, name="any")


@_export
def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply1(lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim),
                  x, name="count_nonzero")


@_export
def cumsum(x, axis=None, dtype=None, name=None):
    dt = convert_dtype(dtype)

    def _cumsum(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=dt)
        return jnp.cumsum(a, axis=int(axis), dtype=dt)
    return apply1(_cumsum, x, name="cumsum")


@_export
def cumprod(x, dim=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    return apply1(lambda a: jnp.cumprod(a, axis=dim, dtype=dt), x,
                  name="cumprod")


@_export
def cummax(x, axis=None, dtype="int64", name=None):
    """Returns (values, indices) like the reference cummax op."""
    def _cm(a):
        ax = axis
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        vals = jax.lax.cummax(a, axis=ax)
        iota = jax.lax.broadcasted_iota(jnp.int64, a.shape, ax)
        is_new_max = a >= vals
        idx_candidates = jnp.where(is_new_max, iota, 0)
        idx = jax.lax.cummax(idx_candidates, axis=ax)
        return vals, idx
    from paddle_tpu.core import apply
    vals, idx = apply(_cm, x, name="cummax")
    idx.stop_gradient = True
    return vals, idx


@_export
def cummin(x, axis=None, dtype="int64", name=None):
    def _cm(a):
        ax = axis
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        vals = jax.lax.cummin(a, axis=ax)
        iota = jax.lax.broadcasted_iota(jnp.int64, a.shape, ax)
        idx_candidates = jnp.where(a <= vals, iota, 0)
        idx = jax.lax.cummax(idx_candidates, axis=ax)
        return vals, idx
    from paddle_tpu.core import apply
    vals, idx = apply(_cm, x, name="cummin")
    idx.stop_gradient = True
    return vals, idx


@_export
def isfinite(x, name=None):
    return apply1(jnp.isfinite, x, name="isfinite")


@_export
def isinf(x, name=None):
    return apply1(jnp.isinf, x, name="isinf")


@_export
def isnan(x, name=None):
    return apply1(jnp.isnan, x, name="isnan")


@_export
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply1(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan), x, y,
                  name="isclose")


@_export
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply1(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                            equal_nan=equal_nan), x, y,
                  name="allclose")


@_export
def equal_all(x, y, name=None):
    return apply1(lambda a, b: jnp.array_equal(a, b), x, y, name="equal_all")


@_export
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    p = _unwrap(prepend) if prepend is not None else None
    ap = _unwrap(append) if append is not None else None
    return apply1(lambda a: jnp.diff(a, n=n, axis=axis, prepend=p, append=ap),
                  x, name="diff")


@_export
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply1(lambda a: jnp.trace(a, offset=offset, axis1=axis1,
                                      axis2=axis2), x, name="trace")


@_export
def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


@_export
def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """operators/metrics/accuracy_op parity."""
    def _acc(pred, lab):
        topk_idx = jax.lax.top_k(pred, k)[1]
        lab2 = lab.reshape(-1, 1)
        correct_ = jnp.any(topk_idx == lab2, axis=1)
        return jnp.mean(correct_.astype(jnp.float32))
    return apply1(_acc, input, label, nondiff=(0, 1), name="accuracy")
