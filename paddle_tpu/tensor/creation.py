"""Tensor creation ops (parity: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import (Tensor, Parameter, apply1, convert_dtype,
                             get_default_dtype, _default_jax_device)

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "meshgrid", "diag", "diagflat", "tril", "triu", "assign", "clone",
    "numel", "tolist", "create_parameter", "create_tensor", "complex",
    "as_tensor",
]


def _resolve_dtype(dtype, default=None):
    if dtype is None:
        return convert_dtype(default) if default is not None else None
    return convert_dtype(dtype)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor parity; place is accepted and ignored (XLA owns it)."""
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        return t
    if isinstance(data, (list, tuple)) and any(
            isinstance(x, Tensor) for x in jax.tree_util.tree_leaves(data)):
        data = np.asarray(jax.tree_util.tree_map(
            lambda x: x.numpy() if isinstance(x, Tensor) else x, data))
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


as_tensor = to_tensor


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in shape.numpy().tolist()]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._data) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None) -> Tensor:
    dtype = _resolve_dtype(dtype, get_default_dtype())
    return Tensor(jnp.zeros(_shape_list(shape), dtype=dtype))


def ones(shape, dtype=None, name=None) -> Tensor:
    dtype = _resolve_dtype(dtype, get_default_dtype())
    return Tensor(jnp.ones(_shape_list(shape), dtype=dtype))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int64
        else:
            dtype = get_default_dtype()
    return Tensor(jnp.full(_shape_list(shape), fill_value,
                           dtype=convert_dtype(dtype)))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype=dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros_like(x._data, dtype=_resolve_dtype(dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones_like(x._data, dtype=_resolve_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.full_like(x._data, fill_value,
                                dtype=_resolve_dtype(dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype=dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (jnp.int64 if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step))
            else get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    dtype = _resolve_dtype(dtype, get_default_dtype())
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=dtype))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    dtype = _resolve_dtype(dtype, get_default_dtype())
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=dtype))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    dtype = _resolve_dtype(dtype, get_default_dtype())
    return Tensor(jnp.eye(num_rows, num_columns, dtype=dtype))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    outs = jnp.meshgrid(*[a._data for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    def _diag(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            idx = jnp.arange(a.shape[0])
            r = idx if offset >= 0 else idx - offset
            c = idx + offset if offset >= 0 else idx
            return base.at[r, c].set(a)
        return jnp.diag(a, k=offset)
    return apply1(_diag, x, name="diag")


def diagflat(x, offset=0, name=None) -> Tensor:
    return apply1(lambda a: jnp.diagflat(a, k=offset), x, name="diagflat")


def tril(x, diagonal=0, name=None) -> Tensor:
    return apply1(lambda a: jnp.tril(a, k=diagonal), x, name="tril")


def triu(x, diagonal=0, name=None) -> Tensor:
    return apply1(lambda a: jnp.triu(a, k=diagonal), x, name="triu")


def assign(x, output=None) -> Tensor:
    val = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    if output is None:
        return apply1(lambda a: a + 0 if jnp.issubdtype(a.dtype, jnp.inexact)
                      else jnp.asarray(a), val, name="assign")
    output.set_value(val)
    return output


def clone(x, name=None) -> Tensor:
    return x.clone()


def numel(x, name=None) -> Tensor:
    return Tensor(np.int64(x.size))


def tolist(x):
    return x.tolist()


def create_tensor(dtype="float32", name=None, persistable=False) -> Tensor:
    return Tensor(jnp.zeros((), dtype=convert_dtype(dtype)),
                  persistable=persistable, name=name)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None) -> Parameter:
    from paddle_tpu.nn.initializer import _create_param
    return _create_param(shape, dtype, attr=attr, is_bias=is_bias,
                         default_initializer=default_initializer, name=name)


def complex(real, imag, name=None) -> Tensor:
    from paddle_tpu.core import apply1 as _a
    return _a(jax.lax.complex, real, imag, name="complex")
