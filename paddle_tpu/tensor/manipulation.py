"""Shape/layout manipulation ops (parity: python/paddle/tensor/manipulation.py).

The reference implements these as C++ kernels (reshape_op.cc, transpose_op.cc,
concat_op.cc, …); here every one is a jnp/lax view op that XLA folds away.
"""
from __future__ import annotations

import builtins
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply, apply1, convert_dtype

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _int_list(v):
    if isinstance(v, Tensor):
        return [int(i) for i in v.numpy().tolist()]
    if isinstance(v, (int, np.integer)):
        return [int(v)]
    return [int(i._data) if isinstance(i, Tensor) else int(i) for i in v]


@_export
def reshape(x, shape, name=None):
    shape = _int_list(shape)
    return apply1(lambda a: jnp.reshape(a, shape), x, name="reshape")


@_export
def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, _int_list(shape))
    return x


@_export
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def _flatten(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply1(_flatten, x, name="flatten")


@_export
def transpose(x, perm, name=None):
    perm = _int_list(perm)
    return apply1(lambda a: jnp.transpose(a, perm), x, name="transpose")


@_export
def moveaxis(x, source, destination, name=None):
    return apply1(lambda a: jnp.moveaxis(a, source, destination), x,
                  name="moveaxis")


@_export
def swapaxes(x, axis1, axis2, name=None):
    return apply1(lambda a: jnp.swapaxes(a, axis1, axis2), x, name="swapaxes")


@_export
def t(x, name=None):
    def _t(a):
        if a.ndim < 2:
            return a
        return a.T
    return apply1(_t, x, name="t")


@_export
def concat(x, axis=0, name=None):
    axis = int(_unwrap(axis)) if not isinstance(axis, int) else axis
    tensors = list(x)
    return apply1(lambda *arrs: jnp.concatenate(arrs, axis=axis), *tensors,
                  name="concat")


@_export
def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply1(lambda *arrs: jnp.stack(arrs, axis=axis), *tensors,
                  name="stack")


@_export
def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    outs = apply(lambda a: tuple(jnp.moveaxis(a, axis, 0)[i] for i in range(n)),
                 x, name="unstack")
    return list(outs)


@_export
def split(x, num_or_sections, axis=0, name=None):
    axis = int(_unwrap(axis)) if not isinstance(axis, int) else axis
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {axis} size {dim} is not divisible by "
                f"num_or_sections={num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = _int_list(num_or_sections)
        n_unknown = builtins.sum(1 for s in sizes if s < 0)
        if n_unknown:
            known = builtins.sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes).tolist()

    def _split(a):
        return tuple(
            jax.lax.slice_in_dim(a, offsets[i], offsets[i + 1], axis=axis)
            for i in range(len(sizes)))
    return list(apply(_split, x, name="split"))


@_export
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


@_export
def squeeze(x, axis=None, name=None):
    def _squeeze(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply1(_squeeze, x, name="squeeze")


squeeze_ = squeeze
__all__.append("squeeze_")


@_export
def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = _int_list(axes)

    def _unsq(a):
        out = a
        for ax in axes:
            out = jnp.expand_dims(out, ax)
        return out
    return apply1(_unsq, x, name="unsqueeze")


unsqueeze_ = unsqueeze
__all__.append("unsqueeze_")


@_export
def tile(x, repeat_times, name=None):
    reps = _int_list(repeat_times)
    return apply1(lambda a: jnp.tile(a, reps), x, name="tile")


@_export
def expand(x, shape, name=None):
    shape = _int_list(shape)

    def _expand(a):
        tgt = list(shape)
        # paddle: -1 means keep dim
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tgt)
    return apply1(_expand, x, name="expand")


@_export
def expand_as(x, y, name=None):
    tgt = tuple(y.shape)
    return apply1(lambda a: jnp.broadcast_to(a, tgt), x, name="expand_as")


@_export
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


@_export
def broadcast_tensors(inputs, name=None):
    arrs = jnp.broadcast_arrays(*[t._data for t in inputs])
    shapes = [a.shape for a in arrs]
    outs = []
    for t, s in zip(inputs, shapes):
        outs.append(apply1(lambda a, _s=s: jnp.broadcast_to(a, _s), t,
                           name="broadcast_tensors"))
    return outs


@_export
def flip(x, axis, name=None):
    axes = _int_list(axis if isinstance(axis, (list, tuple)) else [axis])
    return apply1(lambda a: jnp.flip(a, axis=axes), x, name="flip")


@_export
def rot90(x, k=1, axes=(0, 1), name=None):
    return apply1(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, name="rot90")


@_export
def roll(x, shifts, axis=None, name=None):
    return apply1(lambda a: jnp.roll(a, shifts, axis=axis), x, name="roll")


@_export
def gather(x, index, axis=0, name=None):
    """operators/gather_op parity: select rows of `axis` by 1-D index."""
    axis = int(_unwrap(axis)) if not isinstance(axis, int) else axis
    return apply1(lambda a, idx: jnp.take(a, idx.astype(jnp.int32), axis=axis),
                  x, index, nondiff=(1,), name="gather")


@_export
def gather_nd(x, index, name=None):
    def _gather_nd(a, idx):
        k = idx.shape[-1]
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a[flat_idx] if k == a.ndim else a[flat_idx]
    return apply1(_gather_nd, x, index, nondiff=(1,), name="gather_nd")


@_export
def take_along_axis(arr, indices, axis, name=None):
    return apply1(lambda a, idx: jnp.take_along_axis(a, idx, axis=axis),
                  arr, indices, nondiff=(1,), name="take_along_axis")


@_export
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def _put(a, idx, v):
        v = jnp.broadcast_to(v, idx.shape).astype(a.dtype)
        dims = [jnp.arange(s).reshape([-1 if i == d else 1
                                       for i in range(idx.ndim)])
                for d, s in enumerate(idx.shape)]
        full_idx = tuple(idx if d == axis else jnp.broadcast_to(dims[d], idx.shape)
                         for d in range(idx.ndim))
        if reduce == "assign":
            return a.at[full_idx].set(v)
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[full_idx].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")
    return apply1(_put, arr, indices, values, nondiff=(1,), name="put_along_axis")


@_export
def scatter(x, index, updates, overwrite=True, name=None):
    def _scatter(a, idx, upd):
        idx = idx.reshape(-1).astype(jnp.int32)
        if overwrite:
            return a.at[idx].set(upd)
        base = a.at[idx].set(jnp.zeros_like(upd))
        return base.at[idx].add(upd)
    return apply1(_scatter, x, index, updates, nondiff=(1,), name="scatter")


@_export
def scatter_nd_add(x, index, updates, name=None):
    def _snd(a, idx, upd):
        flat_idx = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[flat_idx].add(upd)
    return apply1(_snd, x, index, updates, nondiff=(1,), name="scatter_nd_add")


@_export
def scatter_nd(index, updates, shape, name=None):
    from paddle_tpu.tensor.creation import zeros
    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


@_export
def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis=axis)


@_export
def index_sample(x, index, name=None):
    return apply1(lambda a, idx: jnp.take_along_axis(a, idx.astype(jnp.int32),
                                                     axis=1),
                  x, index, nondiff=(1,), name="index_sample")


@_export
def index_add(x, index, axis, value, name=None):
    def _ia(a, idx, v):
        idx = idx.astype(jnp.int32)
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = am.at[idx].add(vm)
        return jnp.moveaxis(out, 0, axis)
    return apply1(_ia, x, index, value, nondiff=(1,), name="index_add")


@_export
def slice(input, axes, starts, ends, name=None):
    axes = _int_list(axes)
    starts = _int_list(starts)
    ends = _int_list(ends)

    def _slice(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            dim = a.shape[ax]
            s2 = builtins.max(s + dim, 0) if s < 0 else builtins.min(s, dim)
            e2 = builtins.max(e + dim, 0) if e < 0 else builtins.min(e, dim)
            idx[ax] = builtins.slice(s2, e2)
        return a[tuple(idx)]
    return apply1(_slice, input, name="slice")


@_export
def strided_slice(x, axes, starts, ends, strides, name=None):
    axes, starts = _int_list(axes), _int_list(starts)
    ends, strides = _int_list(ends), _int_list(strides)

    def _ss(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]
    return apply1(_ss, x, name="strided_slice")


@_export
def crop(x, shape=None, offsets=None, name=None):
    shape = _int_list(shape)
    offsets = _int_list(offsets) if offsets is not None else [0] * len(shape)

    def _crop(a):
        idx = tuple(builtins.slice(o, o + (s if s != -1 else a.shape[i] - o))
                    for i, (o, s) in enumerate(zip(offsets, shape)))
        return a[idx]
    return apply1(_crop, x, name="crop")


@_export
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from paddle_tpu.nn.functional.common import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


@_export
def cast(x, dtype):
    dt = convert_dtype(dtype)

    def _cast(a):
        return a.astype(dt)
    return apply1(_cast, x, name="cast")


@_export
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # data-dependent shape → host computation (documented jit-incompatible,
    # same as reference's unique op being CPU-bound for sync mode)
    arr = np.asarray(x._data)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


@_export
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
        work_axis = 0
    else:
        work_axis = axis % arr.ndim
        arr = np.moveaxis(arr, work_axis, 0)
    n = arr.shape[0]
    keep = np.ones(n, dtype=bool)
    if n > 1:
        flat = arr.reshape(n, -1)
        keep[1:] = ~np.all(flat[1:] == flat[:-1], axis=1)
    result = arr[keep]
    if axis is not None:
        result = np.moveaxis(result, 0, work_axis)
    out = [Tensor(result)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(inv.astype(np.int64)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, n))
        out.append(Tensor(counts.astype(np.int64)))
    return out[0] if len(out) == 1 else tuple(out)


@_export
def repeat_interleave(x, repeats, axis=None, name=None):
    r = _unwrap(repeats)
    return apply1(lambda a: jnp.repeat(a, r, axis=axis), x,
                  name="repeat_interleave")


@_export
def as_complex(x, name=None):
    return apply1(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x,
                  name="as_complex")


@_export
def as_real(x, name=None):
    return apply1(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x,
                  name="as_real")


@_export
def real(x, name=None):
    return apply1(jnp.real, x, name="real")


@_export
def imag(x, name=None):
    return apply1(jnp.imag, x, name="imag")


@_export
def tensordot(x, y, axes=2, name=None):
    return apply1(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y,
                  name="tensordot")


@_export
def unbind(input, axis=0):
    return unstack(input, axis=axis)


@_export
def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.tril_indices(row, k=offset, m=col)
    return Tensor(np.stack([r, c]).astype(np.int64))


@_export
def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, k=offset, m=col)
    return Tensor(np.stack([r, c]).astype(np.int64))


@_export
def one_hot(x, num_classes, name=None):
    return apply1(lambda a: jax.nn.one_hot(a, num_classes), x, nondiff=(0,),
                  name="one_hot")


@_export
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """operators/shard_index_op parity (used by parallel embedding)."""
    def _shard(a):
        shard_size = (index_num + nshards - 1) // nshards
        in_shard = (a // shard_size) == shard_id
        return jnp.where(in_shard, a % shard_size, ignore_value)
    return apply1(_shard, input, name="shard_index")
