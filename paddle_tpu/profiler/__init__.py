"""paddle_tpu.profiler — host + device profiling.

Parity targets in the reference:
  * RecordEvent host spans       — platform/profiler.h:127 (RecordEvent),
    python surface fluid/profiler.py record_event
  * start/stop/reset_profiler    — fluid/profiler.py:109-253
  * profiler() context manager   — fluid/profiler.py:255
  * CUPTI device tracing         — platform/device_tracer.cc:57
  * chrome-trace timeline        — tools/timeline.py

TPU mapping: device-side tracing is jax.profiler (XLA's profiler — the
CUPTI analogue), which captures per-op device timelines viewable in
TensorBoard/Perfetto.  Host spans are RecordEvent context managers that
both (a) feed an in-process aggregate table (calls/total/min/max/ave —
the Profiling Report) and (b) emit jax.profiler.TraceAnnotation scopes so
the same names show up inside the device trace.  ``export_chrome_tracing``
writes the host spans in chrome://tracing JSON (timeline.py's role).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["RecordEvent", "record_event", "start_profiler", "stop_profiler",
           "reset_profiler", "profiler", "export_chrome_tracing",
           "is_profiling"]

_state = {
    "on": False,
    "device": False,        # jax.profiler trace running
    "trace_dir": None,
}
_lock = threading.Lock()
# name -> [calls, total, min, max] running aggregates (seconds; calls is
# an int) — O(1) memory per distinct name, however long the profiling run
_events: Dict[str, list] = {}
_spans: List[tuple] = []                      # (name, tid, t0, t1)
_dropped = [0]                                # spans over the retention cap
_t_start = [0.0]


def _max_spans() -> int:
    """Retention cap for the chrome-trace span list (the aggregate
    table above is O(names) regardless).  FLAGS_profiler_max_spans."""
    from paddle_tpu.framework.flags import flag
    return int(flag("profiler_max_spans"))


def is_profiling() -> bool:
    return _state["on"]


class RecordEvent:
    """Named host span (platform/profiler.h:127).  Usable as a context
    manager or decorator.  Always emits a jax TraceAnnotation (so names
    appear in device traces even outside start/stop_profiler); aggregates
    host wall time only while profiling is on."""

    def __init__(self, name: str):
        self.name = name
        self._ann = None
        self._t0 = 0.0

    def __enter__(self):
        if _state["device"] or _state["on"]:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if _state["on"]:
            dur = t1 - self._t0
            with _lock:
                e = _events.get(self.name)
                if e is None:
                    _events[self.name] = [1, dur, dur, dur]
                else:
                    e[0] += 1
                    e[1] += dur
                    if dur < e[2]:
                        e[2] = dur
                    if dur > e[3]:
                        e[3] = dur
                # the aggregate above keeps counting unconditionally;
                # only the per-span timeline is bounded (long profiling
                # runs must not grow host memory without limit)
                if len(_spans) < _max_spans():
                    _spans.append((self.name, threading.get_ident(),
                                   self._t0, t1))
                else:
                    _dropped[0] += 1
        return False

    def __call__(self, fn):
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


@contextlib.contextmanager
def record_event(name: str):
    """fluid/profiler.py record_event parity (contextmanager form)."""
    with RecordEvent(name):
        yield


def reset_profiler():
    """fluid/profiler.py:109."""
    with _lock:
        _events.clear()
        _spans.clear()
        _dropped[0] = 0
    _t_start[0] = time.perf_counter()


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   trace_dir: Optional[str] = None):
    """fluid/profiler.py:131.  state: 'CPU' = host spans only;
    'GPU'/'TPU'/'All' = also start the XLA device trace (written under
    ``trace_dir``, default /tmp/paddle_tpu_profile, TensorBoard format)."""
    if state not in ("CPU", "GPU", "TPU", "All"):
        raise ValueError("state must be 'CPU', 'GPU', 'TPU' or 'All'")
    if tracer_option not in ("Default", "OpDetail", "AllOpDetail"):
        raise ValueError("tracer_option must be 'Default', 'OpDetail' "
                         "or 'AllOpDetail'")
    reset_profiler()
    _state["on"] = True
    if state != "CPU":
        import jax
        d = trace_dir or "/tmp/paddle_tpu_profile"
        os.makedirs(d, exist_ok=True)
        try:
            jax.profiler.start_trace(d)
            _state["device"] = True
            _state["trace_dir"] = d
        except Exception:                      # already tracing, or no device
            _state["device"] = False


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: str = "/tmp/profile"):
    """fluid/profiler.py:198 — stop, print the Profiling Report, and (if a
    device trace was running) finalize it; host spans also go to
    ``profile_path`` as chrome-trace JSON (timeline.py role)."""
    if not _state["on"]:
        return
    if _state["device"]:
        import jax
        jax.profiler.stop_trace()
        _state["device"] = False
    _state["on"] = False
    export_chrome_tracing(profile_path)
    _print_report(sorted_key)


def _print_report(sorted_key):
    if sorted_key not in (None, "calls", "total", "max", "min", "ave"):
        raise ValueError("sorted_key must be one of None/'calls'/'total'/"
                         "'max'/'min'/'ave'")
    with _lock:
        rows = []
        grand = 0.0
        for name, (calls, tot, mn, mx) in _events.items():
            grand += tot
            rows.append((name, calls, tot * 1e3, mn * 1e3,
                         mx * 1e3, tot / calls * 1e3))
        dropped = _dropped[0]
    keyi = {"calls": 1, "total": 2, "min": 3, "max": 4, "ave": 5}
    if sorted_key:
        rows.sort(key=lambda r: r[keyi[sorted_key]], reverse=True)
    print("------------------------->     Profiling Report     "
          "<-------------------------\n")
    print("Place: TPU\nTime unit: ms\nSorted by {} in descending order in "
          "the same thread\n".format(sorted_key or "first end time"))
    hdr = f"{'Event':<32}{'Calls':>8}{'Total':>12}{'Min.':>10}" \
          f"{'Max.':>10}{'Ave.':>10}{'Ratio.':>10}"
    print(hdr)
    for name, calls, tot, mn, mx, ave in rows:
        ratio = tot / (grand * 1e3) if grand else 0.0
        print(f"{name:<32}{calls:>8}{tot:>12.4f}{mn:>10.4f}{mx:>10.4f}"
              f"{ave:>10.4f}{ratio:>10.6f}")
    if dropped:
        print(f"\n{dropped} span(s) dropped from the timeline "
              f"(FLAGS_profiler_max_spans={_max_spans()}); the "
              "aggregates above still count every event")
    if _state["trace_dir"]:
        print(f"\nDevice trace (TensorBoard/XProf): {_state['trace_dir']}")


def export_chrome_tracing(path: str = "/tmp/profile"):
    """Write host RecordEvent spans as chrome://tracing JSON — the
    tools/timeline.py role (its _chrome_trace_format output)."""
    with _lock:
        spans = list(_spans)
        dropped = _dropped[0]
    t0 = _t_start[0]
    events = [{"name": name, "ph": "X", "pid": 0, "tid": tid,
               "ts": (a - t0) * 1e6, "dur": (b - a) * 1e6,
               "cat": "host"} for name, tid, a, b in spans]
    payload = {"traceEvents": events, "displayTimeUnit": "ms",
               "metadata": {"dropped_spans": dropped,
                            "max_spans": _max_spans()}}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: str = "/tmp/profile",
             tracer_option: str = "Default"):
    """fluid/profiler.py:255 context-manager parity."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
