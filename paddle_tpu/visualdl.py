"""VisualDL-compatible experiment logging.

Reference role: the VisualDL LogWriter the reference ecosystem logs to
(visualdl.LogWriter — add_scalar/add_histogram/...) plus hapi's VisualDL
callback.  TPU stack: events are written in TensorBoard format (via
torch's SummaryWriter, baked into this image) so XProf device traces
(paddle_tpu.profiler) and training curves land in one TensorBoard; when
no event-writer backend exists the writer degrades to JSONL scalars so
logging never takes down training.
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

__all__ = ["LogWriter", "VisualDL"]


class LogWriter:
    """visualdl.LogWriter parity (add_scalar/add_text/close; histogram
    degrades to scalar stats in the JSONL backend)."""

    def __init__(self, logdir: str = "vdl_log", **kwargs):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._tb = None
        self._jsonl = None
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._tb = SummaryWriter(log_dir=logdir)
        except Exception:                          # noqa: BLE001
            self._jsonl = open(os.path.join(logdir, "scalars.jsonl"), "a")

    def add_scalar(self, tag: str, value, step: Optional[int] = None):
        value = float(value)
        if self._tb is not None:
            self._tb.add_scalar(tag, value, global_step=step)
        else:
            self._jsonl.write(json.dumps(
                {"tag": tag, "value": value, "step": step,
                 "time": time.time()}) + "\n")
            self._jsonl.flush()

    def add_text(self, tag: str, text: str, step: Optional[int] = None):
        if self._tb is not None:
            self._tb.add_text(tag, text, global_step=step)
        else:
            self._jsonl.write(json.dumps(
                {"tag": tag, "text": text, "step": step,
                 "time": time.time()}) + "\n")
            self._jsonl.flush()

    def add_histogram(self, tag: str, values, step: Optional[int] = None):
        import numpy as np
        arr = np.asarray(values)
        if self._tb is not None:
            self._tb.add_histogram(tag, arr, global_step=step)
        else:
            self._jsonl.write(json.dumps(
                {"tag": tag, "mean": float(arr.mean()),
                 "std": float(arr.std()), "min": float(arr.min()),
                 "max": float(arr.max()), "step": step}) + "\n")
            self._jsonl.flush()

    def flush(self):
        if self._tb is not None:
            self._tb.flush()
        if self._jsonl is not None and not self._jsonl.closed:
            self._jsonl.flush()

    def close(self):
        # idempotent: the context-manager exit and an explicit close()
        # (or two callbacks sharing one writer) may both land here
        if self._tb is not None:
            self._tb.close()
            self._tb = None
        if self._jsonl is not None and not self._jsonl.closed:
            self._jsonl.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _visualdl_base():
    from paddle_tpu.hapi.callbacks import Callback
    return Callback


class VisualDL(_visualdl_base()):
    """hapi callback (reference: paddle.callbacks.VisualDL): logs every
    train/eval metric Model.fit produces."""

    def __init__(self, log_dir: str = "vdl_log"):
        super().__init__()
        self.writer = LogWriter(log_dir)
        self._step = 0

    def _log(self, prefix, logs):
        for k, v in (logs or {}).items():
            try:
                self.writer.add_scalar(f"{prefix}/{k}", float(v),
                                       self._step)
            except (TypeError, ValueError):
                pass                       # non-scalar entries skipped

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._log("train", logs)

    def on_epoch_end(self, epoch, logs=None):
        self._log("epoch", logs)

    def on_eval_end(self, logs=None):
        self._log("eval", logs)

    def on_train_end(self, logs=None):
        self.writer.flush()
