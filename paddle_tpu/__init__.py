"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference snapshot ≈ v2.0/2.1-dev).

Not a port: eager tensors + a vjp tape replace the C++ dygraph engine,
``paddle_tpu.jit`` (to_static) replaces ProgramDesc/Executor with XLA capture,
and ``paddle_tpu.distributed`` replaces NCCL rings with jax.sharding meshes
over ICI/DCN.  See SURVEY.md at the repo root for the layer-by-layer mapping.
"""
from __future__ import annotations

import os as _os

# Full dtype coverage (float64/int64 ops exist in the reference); jax's
# default truncates to 32-bit.  Creation APIs still default to float32
# (paddle semantics), so TPU-hot code stays 32/16-bit.
import jax as _jax
_jax.config.update("jax_enable_x64", True)

# DataLoader worker processes (io._iter_multiprocess) must never grab the
# accelerator out from under the parent — they only run dataset/collate
# python code.  The parent sets this env before spawning.
if _os.environ.get("PADDLE_TPU_WORKER"):
    _jax.config.update("jax_platforms", "cpu")

from paddle_tpu.core import (  # noqa: F401,E402
    Tensor, Parameter, CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace,
    XPUPlace, set_device, get_device, device_count, no_grad, enable_grad,
    is_grad_enabled, set_grad_enabled, get_default_dtype, set_default_dtype,
    convert_dtype, VarDesc,
)
from paddle_tpu import autograd  # noqa: E402,F401
from paddle_tpu.autograd import grad  # noqa: E402,F401
from paddle_tpu.tensor import *  # noqa: F401,F403,E402
from paddle_tpu.tensor import add_n, einsum  # noqa: E402,F401
from paddle_tpu.tensor.random import (  # noqa: E402,F401
    seed, get_rng_state, set_rng_state, default_generator, Generator)

import paddle_tpu.tensor as tensor  # noqa: E402,F401

# dtype singletons, paddle.float32-style
import jax.numpy as _jnp  # noqa: E402
float16 = _jnp.dtype(_jnp.float16)
bfloat16 = _jnp.dtype(_jnp.bfloat16)
float32 = _jnp.dtype(_jnp.float32)
float64 = _jnp.dtype(_jnp.float64)
int8 = _jnp.dtype(_jnp.int8)
uint8 = _jnp.dtype(_jnp.uint8)
int16 = _jnp.dtype(_jnp.int16)
int32 = _jnp.dtype(_jnp.int32)
int64 = _jnp.dtype(_jnp.int64)
bool = _jnp.dtype(_jnp.bool_)  # noqa: A001 — paddle exposes paddle.bool
complex64 = _jnp.dtype(_jnp.complex64)
complex128 = _jnp.dtype(_jnp.complex128)

from paddle_tpu import nn  # noqa: E402,F401
from paddle_tpu import regularizer  # noqa: E402,F401
from paddle_tpu import optimizer  # noqa: E402,F401
from paddle_tpu import framework  # noqa: E402,F401
from paddle_tpu import io  # noqa: E402,F401
from paddle_tpu import metric  # noqa: E402,F401
from paddle_tpu import amp  # noqa: E402,F401
from paddle_tpu import jit  # noqa: E402,F401
from paddle_tpu import static  # noqa: E402,F401
from paddle_tpu import parallel  # noqa: E402,F401
from paddle_tpu import distributed  # noqa: E402,F401
from paddle_tpu import device  # noqa: E402,F401
from paddle_tpu import distribution  # noqa: E402,F401
from paddle_tpu import incubate  # noqa: E402,F401
from paddle_tpu import profiler  # noqa: E402,F401
from paddle_tpu import reader  # noqa: E402,F401
from paddle_tpu import sysconfig  # noqa: E402,F401
from paddle_tpu import version  # noqa: E402,F401
from paddle_tpu.reader import batch  # noqa: E402,F401
from paddle_tpu import quantization  # noqa: E402,F401
from paddle_tpu import vision  # noqa: E402,F401
from paddle_tpu import text  # noqa: E402,F401
from paddle_tpu import models  # noqa: E402,F401
from paddle_tpu import utils  # noqa: E402,F401
from paddle_tpu import visualdl  # noqa: E402,F401
from paddle_tpu import inference  # noqa: E402,F401
from paddle_tpu import onnx  # noqa: E402,F401
from paddle_tpu.framework import monitor  # noqa: E402,F401
from paddle_tpu.distributed.parallel import DataParallel  # noqa: E402,F401
from paddle_tpu.framework.io import save, load  # noqa: E402,F401
from paddle_tpu.hapi.model import Model  # noqa: E402,F401
from paddle_tpu.hapi import summary, flops  # noqa: E402,F401
from paddle_tpu.nn.layer.common import ParamAttr  # noqa: E402,F401

__version__ = "0.2.0"


def is_compiled_with_cuda() -> bool:
    """False: there is no CUDA here — use is_compiled_with_tpu()."""
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    from paddle_tpu.core import _accelerator_platform
    return _accelerator_platform() is not None


def in_dynamic_mode() -> bool:
    return not static._in_static_mode()


def enable_static():
    static._enable_static()


def disable_static():
    static._disable_static()


def disable_signal_handler():
    pass


def set_grad_enabled_(mode):
    set_grad_enabled(mode)


def get_flags(flags):
    from paddle_tpu.framework import flags as _flags
    return _flags.get_flags(flags)


def set_flags(flags):
    from paddle_tpu.framework import flags as _flags
    return _flags.set_flags(flags)


def summary_(*a, **k):  # placeholder to avoid name clash
    raise NotImplementedError


# ---------------------------------------------------------------------------
# fluid-era top-level compat surface (the reference's paddle/__init__.py
# re-exports these; kept as thin aliases so 2.0-era scripts import clean)
# ---------------------------------------------------------------------------
from paddle_tpu.hapi import callbacks  # noqa: E402,F401
from paddle_tpu.framework.selected_rows import SelectedRows as _SR  # noqa: E402

LoDTensor = Tensor          # LoD collapsed into explicit ragged encodings
VarBase = Tensor
LoDTensorArray = list
commit = "tpu-native"
full_version = __version__

elementwise_add = tensor.add
elementwise_sub = tensor.subtract
elementwise_div = tensor.divide
elementwise_floordiv = tensor.floor_divide
elementwise_mod = tensor.remainder
elementwise_pow = tensor.pow
reduce_sum = tensor.sum
reduce_mean = tensor.mean
reduce_max = tensor.max
reduce_min = tensor.min
reduce_prod = tensor.prod
crop_tensor = tensor.crop


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    """fluid.layers.fill_constant argument order (shape, dtype, value)."""
    return tensor.full(shape, value, dtype=dtype)


def broadcast_shape(x_shape, y_shape):
    return list(_jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def rank(input):
    import numpy as _np
    from paddle_tpu.core import Tensor as _T
    return _T(_np.int64(input.ndim))


def shape(input):
    from paddle_tpu.core import Tensor as _T
    import numpy as _np
    return _T(_np.asarray(input.shape, _np.int32))


def _has_any(fn, x):
    from paddle_tpu.core import apply1
    return apply1(lambda a: fn(a).any(), x, name="has_check")


def has_nan(x):
    return _has_any(_jnp.isnan, x)


def has_inf(x):
    return _has_any(_jnp.isinf, x)


def _inplace_apply(x, fn, *args, **kwargs):
    """In-place op semantics that stay on the tape: the op consumes a
    clone carrying x's old graph position, then x adopts the tracked
    result — so later backward sees the op (the role of the reference's
    inplace version counters).  Leaf tensors that require grad keep a
    data-only update (differentiating through in-place mutation of a
    leaf is rejected by the reference/torch too)."""
    from paddle_tpu.core import Tensor as _T
    if x._node is None and not x.stop_gradient:
        out = fn(x, *args, **kwargs)
        x._data = out._data
        return x
    pre = _T(x._data, stop_gradient=x.stop_gradient)
    pre._node = x._node
    pre._out_index = getattr(x, "_out_index", 0)
    pre.is_leaf_ = getattr(x, "is_leaf_", True)
    out = fn(pre, *args, **kwargs)
    x._data = out._data
    x._node = out._node
    x._out_index = getattr(out, "_out_index", 0)
    x.is_leaf_ = getattr(out, "is_leaf_", True)
    x.stop_gradient = out.stop_gradient
    return x


def tanh_(x):
    return _inplace_apply(x, tensor.tanh)


def scatter_(x, index, updates, overwrite=True):
    # same semantics as tensor.scatter (overwrite=False zeroes target rows
    # before accumulating, per scatter_op.h), applied in place
    return _inplace_apply(x, tensor.scatter, index, updates,
                          overwrite=overwrite)


def get_tensor_from_selected_rows(x):
    from paddle_tpu.core import Tensor as _T
    return _T(x.to_dense()) if isinstance(x, _SR) else x


def in_dygraph_mode():
    return in_dynamic_mode()


def enable_dygraph(place=None):
    disable_static()


def disable_dygraph():
    enable_static()


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from paddle_tpu.tensor.creation import full as _full
    t = _full(shape, value, dtype=dtype)
    t.stop_gradient = True     # global vars (counters, lr) are never
    t.persistable = persistable  # grad-tracked; persistable is metadata
    return t


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)


def get_cudnn_version():
    return None          # no cuDNN here; XLA owns kernel selection


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def data(name, shape, dtype="float32", lod_level=0):
    """static data layer → InputSpec (the capture-tier equivalent)."""
    from paddle_tpu.static import InputSpec
    return InputSpec(shape, dtype=dtype, name=name)


# last: the 1.x compat namespaces close the import cycle over this module
from paddle_tpu import fluid  # noqa: E402
from paddle_tpu import dataset  # noqa: E402,F401
