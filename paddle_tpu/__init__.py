"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference snapshot ≈ v2.0/2.1-dev).

Not a port: eager tensors + a vjp tape replace the C++ dygraph engine,
``paddle_tpu.jit`` (to_static) replaces ProgramDesc/Executor with XLA capture,
and ``paddle_tpu.distributed`` replaces NCCL rings with jax.sharding meshes
over ICI/DCN.  See SURVEY.md at the repo root for the layer-by-layer mapping.
"""
from __future__ import annotations

import os as _os

# Full dtype coverage (float64/int64 ops exist in the reference); jax's
# default truncates to 32-bit.  Creation APIs still default to float32
# (paddle semantics), so TPU-hot code stays 32/16-bit.
import jax as _jax
_jax.config.update("jax_enable_x64", True)

# DataLoader worker processes (io._iter_multiprocess) must never grab the
# accelerator out from under the parent — they only run dataset/collate
# python code.  The parent sets this env before spawning.
if _os.environ.get("PADDLE_TPU_WORKER"):
    _jax.config.update("jax_platforms", "cpu")

from paddle_tpu.core import (  # noqa: F401,E402
    Tensor, Parameter, CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace,
    XPUPlace, set_device, get_device, device_count, no_grad, enable_grad,
    is_grad_enabled, set_grad_enabled, get_default_dtype, set_default_dtype,
    convert_dtype, VarDesc,
)
from paddle_tpu import autograd  # noqa: E402,F401
from paddle_tpu.autograd import grad  # noqa: E402,F401
from paddle_tpu.tensor import *  # noqa: F401,F403,E402
from paddle_tpu.tensor import add_n, einsum  # noqa: E402,F401
from paddle_tpu.tensor.random import (  # noqa: E402,F401
    seed, get_rng_state, set_rng_state, default_generator, Generator)

import paddle_tpu.tensor as tensor  # noqa: E402,F401

# dtype singletons, paddle.float32-style
import jax.numpy as _jnp  # noqa: E402
float16 = _jnp.dtype(_jnp.float16)
bfloat16 = _jnp.dtype(_jnp.bfloat16)
float32 = _jnp.dtype(_jnp.float32)
float64 = _jnp.dtype(_jnp.float64)
int8 = _jnp.dtype(_jnp.int8)
uint8 = _jnp.dtype(_jnp.uint8)
int16 = _jnp.dtype(_jnp.int16)
int32 = _jnp.dtype(_jnp.int32)
int64 = _jnp.dtype(_jnp.int64)
bool = _jnp.dtype(_jnp.bool_)  # noqa: A001 — paddle exposes paddle.bool
complex64 = _jnp.dtype(_jnp.complex64)
complex128 = _jnp.dtype(_jnp.complex128)

from paddle_tpu import nn  # noqa: E402,F401
from paddle_tpu import regularizer  # noqa: E402,F401
from paddle_tpu import optimizer  # noqa: E402,F401
from paddle_tpu import framework  # noqa: E402,F401
from paddle_tpu import io  # noqa: E402,F401
from paddle_tpu import metric  # noqa: E402,F401
from paddle_tpu import amp  # noqa: E402,F401
from paddle_tpu import jit  # noqa: E402,F401
from paddle_tpu import static  # noqa: E402,F401
from paddle_tpu import parallel  # noqa: E402,F401
from paddle_tpu import distributed  # noqa: E402,F401
from paddle_tpu import distribution  # noqa: E402,F401
from paddle_tpu import profiler  # noqa: E402,F401
from paddle_tpu import quantization  # noqa: E402,F401
from paddle_tpu import vision  # noqa: E402,F401
from paddle_tpu import text  # noqa: E402,F401
from paddle_tpu import models  # noqa: E402,F401
from paddle_tpu import utils  # noqa: E402,F401
from paddle_tpu import visualdl  # noqa: E402,F401
from paddle_tpu import inference  # noqa: E402,F401
from paddle_tpu import onnx  # noqa: E402,F401
from paddle_tpu.distributed.parallel import DataParallel  # noqa: E402,F401
from paddle_tpu.framework.io import save, load  # noqa: E402,F401
from paddle_tpu.hapi.model import Model  # noqa: E402,F401
from paddle_tpu.hapi import summary, flops  # noqa: E402,F401
from paddle_tpu.nn.layer.common import ParamAttr  # noqa: E402,F401

__version__ = "0.2.0"


def is_compiled_with_cuda() -> bool:
    """False: there is no CUDA here — use is_compiled_with_tpu()."""
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    from paddle_tpu.core import _accelerator_platform
    return _accelerator_platform() is not None


def in_dynamic_mode() -> bool:
    return not static._in_static_mode()


def enable_static():
    static._enable_static()


def disable_static():
    static._disable_static()


def disable_signal_handler():
    pass


def set_grad_enabled_(mode):
    set_grad_enabled(mode)


def get_flags(flags):
    from paddle_tpu.framework import flags as _flags
    return _flags.get_flags(flags)


def set_flags(flags):
    from paddle_tpu.framework import flags as _flags
    return _flags.set_flags(flags)


def summary_(*a, **k):  # placeholder to avoid name clash
    raise NotImplementedError
