"""Text datasets (parity: python/paddle/text/datasets/*.py).

Zero-egress: each dataset reads the reference's standard archive format
from a local path (default: the reference's download-cache location
~/.cache/paddle/dataset); ``FakeTextDataset`` supplies synthetic token
streams for tests and benchmarks.
"""
from __future__ import annotations

import os
import re
import tarfile
from typing import Callable, Optional

import numpy as np

from paddle_tpu.io import Dataset
from paddle_tpu.io.dataset_cache import CACHE_ROOT as _CACHE, require_file

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "FakeTextDataset"]


def _need(path, name):
    return require_file(name, path)


class Imdb(Dataset):
    """IMDB sentiment (reference: text/datasets/imdb.py; aclImdb_v1 tar)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150, word_idx: Optional[dict] = None):
        self.mode = mode
        data_file = data_file or os.path.join(_CACHE, "imdb",
                                              "aclImdb_v1.tar.gz")
        _need(data_file, "Imdb")
        # vocab is built over train+test (reference imdb.py _build_work_dict
        # scans aclImdb/((train)|(test))/...), so both modes share ids.  A
        # caller-supplied word_idx (the 1.x reader-creator contract, where
        # imdb.train(word_idx) tokenizes with the dict the caller built)
        # skips the freq pass and is used verbatim.
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        mode_pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                vm = vocab_pat.match(member.name)
                if not vm:
                    continue
                if word_idx is not None and not mode_pat.match(member.name):
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                words = re.sub(r"[^a-z]+", " ", text).split()
                if word_idx is None:
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
                if mode_pat.match(member.name):
                    docs.append(words)
                    labels.append(0 if vm.group(2) == "pos" else 1)
        if word_idx is not None:
            self.word_idx = dict(word_idx)
        else:
            kept = [w for w, c in sorted(freq.items(),
                                         key=lambda kv: (-kv[1], kv[0]))
                    if c > cutoff]  # reference keeps freq > cutoff
            self.word_idx = {w: i for i, w in enumerate(kept)}
            self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram LM dataset (reference: text/datasets/imikolov.py)."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50,
                 word_idx: Optional[dict] = None):
        data_file = data_file or os.path.join(
            _CACHE, "imikolov", "simple-examples.tgz")
        _need(data_file, "Imikolov")
        member = {"train": "./simple-examples/data/ptb.train.txt",
                  "test": "./simple-examples/data/ptb.valid.txt"}[mode]
        with tarfile.open(data_file) as tf:
            if word_idx is None:
                freq = {}
                train = tf.extractfile(
                    "./simple-examples/data/ptb.train.txt").read().decode()
                for w in train.split():
                    freq[w] = freq.get(w, 0) + 1
            text = tf.extractfile(member).read().decode()
        if word_idx is not None:
            # 1.x reader-creator contract: ids come from the dict the
            # caller built (possibly with a non-default min_word_freq)
            self.word_idx = dict(word_idx)
        else:
            # reference build_dict keeps strictly freq > min_word_freq
            vocab = [w for w, c in sorted(freq.items(),
                                          key=lambda kv: (-kv[1], kv[0]))
                     if c > min_word_freq and w != "<unk>"]
            self.word_idx = {w: i for i, w in enumerate(vocab)}
            self.word_idx["<unk>"] = len(self.word_idx)
            self.word_idx["<s>"] = len(self.word_idx)
            self.word_idx["<e>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for line in text.split("\n"):
            words = (["<s>"] + line.split() + ["<e>"])
            ids = [self.word_idx.get(w, unk) for w in words]
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.asarray(ids[i:i + window_size],
                                                np.int64))
            else:
                self.data.append(np.asarray(ids, np.int64))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (reference: text/datasets/uci_housing.py;
    reads the standard housing.data whitespace table)."""

    FEATURE_NUM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        data_file = data_file or os.path.join(_CACHE, "uci_housing",
                                              "housing.data")
        _need(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats = raw[:, :-1]
        mn, mx = feats.min(0), feats.max(0)
        feats = (feats - feats.mean(0)) / np.maximum(mx - mn, 1e-6)
        split = int(len(raw) * 0.8)
        sl = slice(0, split) if mode == "train" else slice(split, None)
        self.data = feats[sl]
        self.targets = raw[sl, -1:].astype(np.float32)

    def __getitem__(self, idx):
        return self.data[idx], self.targets[idx]

    def __len__(self):
        return len(self.data)


_ML_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]   # movielens.py:31


class Movielens(Dataset):
    """MovieLens ml-1m ratings (reference: text/datasets/movielens.py).

    Sample (movielens.py _load_data): ([uid], [gender], [age_idx], [job],
    [mov_id], [category_ids...], [title_word_ids...], [rating*2-5]).
    Train/test split by the same seeded-random 0.1 holdout."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        import zipfile
        self.mode = mode
        data_file = data_file or os.path.join(_CACHE, "movielens",
                                              "ml-1m.zip")
        _need(data_file, "Movielens")
        self.categories_dict = {}
        self.movie_title_dict = {}
        movie_info = {}
        user_info = {}
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin1").strip() \
                        .split("::")
                    title_words = title.split()
                    for c in cats.split("|"):
                        self.categories_dict.setdefault(
                            c, len(self.categories_dict))
                    for w in title_words:
                        self.movie_title_dict.setdefault(
                            w.lower(), len(self.movie_title_dict))
                    movie_info[int(mid)] = (int(mid), cats.split("|"),
                                            title_words)
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _zip = line.decode(
                        "latin1").strip().split("::")
                    user_info[int(uid)] = (
                        int(uid), 0 if gender == "M" else 1,
                        _ML_AGE_TABLE.index(int(age)), int(job))
            rng = np.random.RandomState(rand_seed)
            is_test = mode == "test"
            self.data = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ts = line.decode(
                        "latin1").strip().split("::")
                    u = user_info[int(uid)]
                    m = movie_info[int(mid)]
                    self.data.append((
                        [u[0]], [u[1]], [u[2]], [u[3]], [m[0]],
                        [self.categories_dict[c] for c in m[1]],
                        [self.movie_title_dict[w.lower()] for w in m[2]],
                        [float(rating) * 2 - 5.0]))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


_WMT_START, _WMT_END, _WMT_UNK, _WMT_UNK_IDX = "<s>", "<e>", "<unk>", 2


class WMT14(Dataset):
    """WMT14 en→fr subset (reference: text/datasets/wmt14.py).

    Archive layout: ``*src.dict``/``*trg.dict`` vocab files plus
    ``{mode}/{mode}`` parallel files of ``src\\ttrg`` lines.  Samples
    (wmt14.py:158-166): (<s> src <e> ids, <s>+trg ids, trg+<e> ids),
    sequences longer than 80 dropped."""

    def __init__(self, data_file=None, mode="train", dict_size=-1):
        assert mode in ("train", "test", "gen"), mode
        self.mode = mode
        data_file = data_file or os.path.join(_CACHE, "wmt14", "wmt14.tgz")
        _need(data_file, "WMT14")
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file) as tf:
            def to_dict(name):
                members = [m for m in tf.getmembers()
                           if m.name.endswith(name)]
                assert len(members) == 1, name
                out = {}
                for i, line in enumerate(tf.extractfile(members[0])):
                    if dict_size > 0 and i >= dict_size:
                        break
                    out[line.decode("utf-8").strip()] = i
                return out
            self.src_dict = to_dict("src.dict")
            self.trg_dict = to_dict("trg.dict")
            fname = f"{mode}/{mode}"
            for m in tf.getmembers():
                if not m.name.endswith(fname):
                    continue
                for line in tf.extractfile(m):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, _WMT_UNK_IDX) for w in
                           [_WMT_START] + parts[0].split() + [_WMT_END]]
                    trg = [self.trg_dict.get(w, _WMT_UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src)
                    self.trg_ids.append(
                        [self.trg_dict[_WMT_START]] + trg)
                    self.trg_ids_next.append(
                        trg + [self.trg_dict[_WMT_END]])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    """WMT16 en↔de (reference: text/datasets/wmt16.py): tarball with
    ``wmt16/{train,val,test}`` files of ``en\\tde`` lines; vocabularies
    built from the train corpus (top-k by frequency, after <s>/<e>/<unk>).
    """

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en"):
        assert mode in ("train", "test", "val"), mode
        self.mode = mode
        self.lang = lang
        data_file = data_file or os.path.join(_CACHE, "wmt16",
                                              "wmt16.tar.gz")
        _need(data_file, "WMT16")
        src_col = 0 if lang == "en" else 1
        trg_col = 1 - src_col
        with tarfile.open(data_file) as tf:
            def build_dict(col, size):
                freq = {}
                for line in tf.extractfile("wmt16/train"):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    for w in parts[col].split():
                        freq[w] = freq.get(w, 0) + 1
                words = sorted(freq, key=lambda w: (-freq[w], w))
                if size > 0:
                    words = words[:max(0, size - 3)]
                d = {_WMT_START: 0, _WMT_END: 1, _WMT_UNK: 2}
                for w in words:
                    d[w] = len(d)
                return d
            self.src_dict = build_dict(src_col, src_dict_size)
            self.trg_dict = build_dict(trg_col, trg_dict_size)
            self.data = []
            for line in tf.extractfile(f"wmt16/{mode}"):
                parts = line.decode("utf-8").strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [self.src_dict.get(w, _WMT_UNK_IDX)
                       for w in parts[src_col].split()]
                trg = [self.trg_dict.get(w, _WMT_UNK_IDX)
                       for w in parts[trg_col].split()]
                self.data.append((
                    [0] + src + [1], [0] + trg, trg + [1]))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """CoNLL-2005 SRL test set (reference: text/datasets/conll05.py).

    Parses ``test.wsj.words.gz`` + ``test.wsj.props.gz`` star-bracket
    annotations into per-predicate samples (conll05.py:288):
    (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2 — each repeated to
    sentence length — predicate_id, mark, BIO label_ids).  Word/verb/label
    dictionaries are built from the corpus when the reference's separate
    dict files are absent."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, **kw):
        import gzip
        data_file = data_file or os.path.join(_CACHE, "conll05st",
                                              "conll05st-tests.tar.gz")
        _need(data_file, "Conll05st")
        sentences, predicates, labels = [], [], []
        with tarfile.open(data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words_f, \
                    gzip.GzipFile(fileobj=pf) as props_f:
                one_seg, sent = [], []
                for wline, pline in zip(words_f, props_f):
                    word = wline.decode("utf-8").strip()
                    cols = pline.decode("utf-8").strip().split()
                    if not cols:                  # sentence boundary
                        self._flush(sent, one_seg, sentences, predicates,
                                    labels)
                        one_seg, sent = [], []
                        continue
                    sent.append(word)
                    one_seg.append(cols)
                self._flush(sent, one_seg, sentences, predicates, labels)

        def load_dict(path, items):
            if path and os.path.exists(path):
                with open(path) as f:
                    return {w.strip(): i for i, w in enumerate(f)}
            vocab = {}
            for it in items:
                for w in (it if isinstance(it, list) else [it]):
                    vocab.setdefault(w, len(vocab))
            return vocab

        self.word_dict = load_dict(word_dict_file, sentences + [["bos",
                                                                 "eos"]])
        self.predicate_dict = load_dict(verb_dict_file, predicates)
        self.label_dict = load_dict(target_dict_file, labels)
        self._samples = list(zip(sentences, predicates, labels))

    @staticmethod
    def _flush(sent, one_seg, sentences, predicates, labels):
        """conll05.py:190-236: column 0 is the predicate lemma column;
        each further column is one predicate's star-bracket tag sequence."""
        if not one_seg:
            return
        cols = [[row[i] for row in one_seg]
                for i in range(len(one_seg[0]))]
        verbs = [v for v in cols[0] if v != "-"]
        for i, col in enumerate(cols[1:]):
            seq, cur, inside = [], "O", False
            for tag in col:
                if tag == "*":
                    seq.append("I-" + cur if inside else "O")
                elif tag == "*)":
                    seq.append("I-" + cur)
                    inside = False
                elif "(" in tag:
                    cur = tag[1:tag.find("*")]
                    seq.append("B-" + cur)
                    inside = ")" not in tag
                else:
                    raise RuntimeError(f"unexpected SRL tag {tag}")
            if i < len(verbs):
                sentences.append(list(sent))
                predicates.append(verbs[i])
                labels.append(seq)

    def __getitem__(self, idx):
        """conll05.py:239-290 feature construction."""
        sent, verb, lbl = self._samples[idx]
        n = len(sent)
        try:
            vi = sent.index(verb)
        except ValueError:
            vi = next(i for i, l in enumerate(lbl) if l.startswith("B-V")) \
                if any(l.startswith("B-V") for l in lbl) else 0
        mark = [0] * n
        ctx = []
        for off in (-2, -1, 0, 1, 2):
            j = vi + off
            if 0 <= j < n:
                ctx.append(sent[j])
                mark[j] = 1
            else:
                ctx.append("bos" if j < 0 else "eos")
        unk = self.word_dict.get("<unk>", 0)
        word_idx = [self.word_dict.get(w, unk) for w in sent]
        ctx_idx = [[self.word_dict.get(c, unk)] * n for c in ctx]
        pred_idx = [self.predicate_dict[verb]] * n
        label_idx = [self.label_dict[l] for l in lbl]
        return (np.array(word_idx), np.array(ctx_idx[0]),
                np.array(ctx_idx[1]), np.array(ctx_idx[2]),
                np.array(ctx_idx[3]), np.array(ctx_idx[4]),
                np.array(pred_idx), np.array(mark), np.array(label_idx))

    def __len__(self):
        return len(self._samples)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict


class FakeTextDataset(Dataset):
    """Synthetic token-sequence dataset for LM tests/benchmarks."""

    def __init__(self, num_samples=256, seq_len=128, vocab_size=1000,
                 num_classes: Optional[int] = None, seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.num_classes = num_classes
        self._seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed * 999_983 + idx)
        ids = rng.integers(0, self.vocab_size,
                           size=(self.seq_len,)).astype(np.int64)
        if self.num_classes is not None:
            return ids, np.int64(rng.integers(0, self.num_classes))
        return ids

    def __len__(self):
        return self.num_samples
