"""Text datasets (parity: python/paddle/text/datasets/*.py).

Zero-egress: each dataset reads the reference's standard archive format
from a local path (default: the reference's download-cache location
~/.cache/paddle/dataset); ``FakeTextDataset`` supplies synthetic token
streams for tests and benchmarks.
"""
from __future__ import annotations

import os
import re
import tarfile
from typing import Callable, Optional

import numpy as np

from paddle_tpu.io import Dataset
from paddle_tpu.io.dataset_cache import CACHE_ROOT as _CACHE, require_file

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "FakeTextDataset"]


def _need(path, name):
    return require_file(name, path)


class Imdb(Dataset):
    """IMDB sentiment (reference: text/datasets/imdb.py; aclImdb_v1 tar)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150):
        self.mode = mode
        data_file = data_file or os.path.join(_CACHE, "imdb",
                                              "aclImdb_v1.tar.gz")
        _need(data_file, "Imdb")
        # vocab is built over train+test (reference imdb.py _build_work_dict
        # scans aclImdb/((train)|(test))/...), so both modes share ids
        vocab_pat = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        mode_pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                vm = vocab_pat.match(member.name)
                if not vm:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                words = re.sub(r"[^a-z]+", " ", text).split()
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
                if mode_pat.match(member.name):
                    docs.append(words)
                    labels.append(0 if vm.group(2) == "pos" else 1)
        kept = [w for w, c in sorted(freq.items(),
                                     key=lambda kv: (-kv[1], kv[0]))
                if c > cutoff]  # reference keeps freq > cutoff
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram LM dataset (reference: text/datasets/imikolov.py)."""

    def __init__(self, data_file: Optional[str] = None, data_type="NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50):
        data_file = data_file or os.path.join(
            _CACHE, "imikolov", "simple-examples.tgz")
        _need(data_file, "Imikolov")
        member = {"train": "./simple-examples/data/ptb.train.txt",
                  "test": "./simple-examples/data/ptb.valid.txt"}[mode]
        freq = {}
        with tarfile.open(data_file) as tf:
            train = tf.extractfile(
                "./simple-examples/data/ptb.train.txt").read().decode()
            for w in train.split():
                freq[w] = freq.get(w, 0) + 1
            text = tf.extractfile(member).read().decode()
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c >= min_word_freq and w != "<unk>"]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        self.word_idx["<s>"] = len(self.word_idx)
        self.word_idx["<e>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for line in text.split("\n"):
            words = (["<s>"] + line.split() + ["<e>"])
            ids = [self.word_idx.get(w, unk) for w in words]
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.asarray(ids[i:i + window_size],
                                                np.int64))
            else:
                self.data.append(np.asarray(ids, np.int64))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (reference: text/datasets/uci_housing.py;
    reads the standard housing.data whitespace table)."""

    FEATURE_NUM = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        data_file = data_file or os.path.join(_CACHE, "uci_housing",
                                              "housing.data")
        _need(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats = raw[:, :-1]
        mn, mx = feats.min(0), feats.max(0)
        feats = (feats - feats.mean(0)) / np.maximum(mx - mn, 1e-6)
        split = int(len(raw) * 0.8)
        sl = slice(0, split) if mode == "train" else slice(split, None)
        self.data = feats[sl]
        self.targets = raw[sl, -1:].astype(np.float32)

    def __getitem__(self, idx):
        return self.data[idx], self.targets[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    def __init__(self, data_file=None, mode="train", **kw):
        data_file = data_file or os.path.join(_CACHE, "movielens",
                                              "ml-1m.zip")
        _need(data_file, "Movielens")
        raise NotImplementedError("Movielens parsing: round-2 scope")


class WMT14(Dataset):
    def __init__(self, data_file=None, mode="train", dict_size=30000):
        data_file = data_file or os.path.join(
            _CACHE, "wmt14", "wmt14.tgz")
        _need(data_file, "WMT14")
        raise NotImplementedError("WMT14 parsing: round-2 scope")


class WMT16(WMT14):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        data_file = data_file or os.path.join(_CACHE, "wmt16", "wmt16.tar.gz")
        _need(data_file, "WMT16")
        raise NotImplementedError("WMT16 parsing: round-2 scope")


class Conll05st(Dataset):
    def __init__(self, data_file=None, **kw):
        data_file = data_file or os.path.join(_CACHE, "conll05st",
                                              "conll05st-tests.tar.gz")
        _need(data_file, "Conll05st")
        raise NotImplementedError("Conll05st parsing: round-2 scope")


class FakeTextDataset(Dataset):
    """Synthetic token-sequence dataset for LM tests/benchmarks."""

    def __init__(self, num_samples=256, seq_len=128, vocab_size=1000,
                 num_classes: Optional[int] = None, seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.num_classes = num_classes
        self._seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self._seed * 999_983 + idx)
        ids = rng.integers(0, self.vocab_size,
                           size=(self.seq_len,)).astype(np.int64)
        if self.num_classes is not None:
            return ids, np.int64(rng.integers(0, self.num_classes))
        return ids

    def __len__(self):
        return self.num_samples
