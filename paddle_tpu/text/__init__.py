"""paddle_tpu.text (parity: python/paddle/text/ — datasets Imdb, Imikolov,
Movielens, UCIHousing, WMT14/16, Conll05st)."""
from paddle_tpu.text.datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
    FakeTextDataset)

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "FakeTextDataset"]
