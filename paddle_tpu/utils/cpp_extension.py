"""Custom C++ op runtime — paddle.utils.cpp_extension parity.

Reference: paddle/fluid/framework/custom_operator.cc (runtime .so load +
op registration over a stable C ABI) and
python/paddle/utils/cpp_extension/ (load(): g++ the user's sources, then
expose the ops to python).

TPU mapping: *device* custom kernels are written in Pallas
(paddle_tpu/ops/pallas — that is the custom-kernel story for the MXU);
this module covers the reference's *host* custom-op capability: user C++
compiled at runtime and registered as a differentiable framework op.
The op executes on host via ``jax.pure_callback`` wrapped in a
``jax.custom_vjp``, so it works eagerly, under ``jit`` capture, and on
the tape (backward uses the user's ``*_backward`` symbol when present).

C ABI contract (elementwise/same-shape ops — the overwhelmingly common
custom-op case; richer signatures belong in Pallas):

    extern "C" void <name>_forward(const float* x, long long n,
                                   float* out);
    extern "C" void <name>_backward(const float* x, const float* gout,
                                    long long n, float* gin);   // optional
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import Tensor, apply1

__all__ = ["load", "CustomOp"]

_CACHE_DIR = os.path.join(tempfile.gettempdir(), "paddle_tpu_custom_ops")


def _compile(source_path: str, tag: str) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so = os.path.join(_CACHE_DIR, f"{tag}.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(
            source_path):
        return so
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", source_path,
           "-o", so + ".tmp"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=180)
    if r.returncode != 0:
        raise RuntimeError(f"custom op build failed:\n{r.stderr[-2000:]}")
    os.replace(so + ".tmp", so)
    return so


class CustomOp:
    """One loaded op: callable on Tensors, differentiable when the
    backward symbol exists."""

    def __init__(self, name: str, lib: ctypes.CDLL):
        self.name = name
        self._fwd = getattr(lib, f"{name}_forward")
        self._fwd.argtypes = [ctypes.POINTER(ctypes.c_float),
                              ctypes.c_longlong,
                              ctypes.POINTER(ctypes.c_float)]
        self._bwd = getattr(lib, f"{name}_backward", None)
        if self._bwd is not None:
            self._bwd.argtypes = [ctypes.POINTER(ctypes.c_float),
                                  ctypes.POINTER(ctypes.c_float),
                                  ctypes.c_longlong,
                                  ctypes.POINTER(ctypes.c_float)]
        self._jax_fn = self._build()

    # -- host callbacks ------------------------------------------------------
    def _run_fwd(self, x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(x)
        self._fwd(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size,
                  out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out

    def _run_bwd(self, x: np.ndarray, gout: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        gout = np.ascontiguousarray(gout, np.float32)
        gin = np.empty_like(x)
        self._bwd(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  gout.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  x.size,
                  gin.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return gin

    def _build(self):
        def call_fwd(x):
            return jax.pure_callback(
                self._run_fwd, jax.ShapeDtypeStruct(x.shape, jnp.float32),
                x, vmap_method="sequential")

        if self._bwd is None:
            return call_fwd

        @jax.custom_vjp
        def op(x):
            return call_fwd(x)

        def fwd(x):
            return call_fwd(x), x

        def bwd(x, g):
            gin = jax.pure_callback(
                self._run_bwd, jax.ShapeDtypeStruct(x.shape, jnp.float32),
                x, g, vmap_method="sequential")
            return (gin,)

        op.defvjp(fwd, bwd)
        return op

    def __call__(self, x):
        if isinstance(x, Tensor):
            return apply1(self._jax_fn, x, name=self.name)
        return self._jax_fn(jnp.asarray(x))


class _OpModule:
    def __init__(self, ops):
        for op in ops:
            setattr(self, op.name, op)
        self._ops = {op.name: op for op in ops}

    def __iter__(self):
        return iter(self._ops.values())


def load(name: str, sources=None, source_code: Optional[str] = None,
         functions=None, verbose: bool = False):
    """cpp_extension.load parity: compile sources (or inline
    ``source_code``) and return a module whose attributes are the ops
    named in ``functions`` (default: derived from ``name``)."""
    if source_code is not None:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        tag = name + "_" + hashlib.sha1(
            source_code.encode()).hexdigest()[:12]
        src = os.path.join(_CACHE_DIR, tag + ".cpp")
        if not os.path.exists(src):
            with open(src, "w") as f:
                f.write(source_code)
    elif sources:
        src = sources[0]
        with open(src, "rb") as f:
            tag = name + "_" + hashlib.sha1(f.read()).hexdigest()[:12]
    else:
        raise ValueError("pass sources=[...] or source_code=...")
    so = _compile(src, tag)
    lib = ctypes.CDLL(so)
    fns = functions or [name]
    ops = [CustomOp(fn, lib) for fn in fns]
    if verbose:
        print(f"loaded custom ops {fns} from {so}")
    if len(ops) == 1:
        return ops[0]
    return _OpModule(ops)
