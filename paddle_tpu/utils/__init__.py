"""paddle.utils parity tier: custom-op runtime (cpp_extension),
@deprecated, install run_check, weights-cache download."""
from paddle_tpu.utils import cpp_extension  # noqa: F401
from paddle_tpu.utils import download  # noqa: F401
from paddle_tpu.utils.deprecated import deprecated  # noqa: F401
from paddle_tpu.utils.install_check import run_check  # noqa: F401

__all__ = ["cpp_extension", "download", "deprecated", "run_check"]
