"""paddle.utils parity tier: custom-op runtime (cpp_extension)."""
from paddle_tpu.utils import cpp_extension  # noqa: F401
