"""paddle.utils.download (parity: python/paddle/utils/download.py —
get_weights_path_from_url with a local cache).  This environment has
zero egress, so the cache is the only source: a URL whose file is
already cached resolves; anything else raises with a clear message
instead of hanging on a socket.
"""
from __future__ import annotations

import hashlib
import os

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/hapi/weights")


def _map_path(url: str) -> str:
    fname = os.path.basename(url.split("?")[0]) or \
        hashlib.md5(url.encode()).hexdigest()
    return os.path.join(WEIGHTS_HOME, fname)


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    path = _map_path(url)
    if os.path.exists(path):
        if md5sum:
            with open(path, "rb") as f:
                if hashlib.md5(f.read()).hexdigest() != md5sum:
                    raise IOError(
                        f"cached file {path} fails its md5 check")
        return path
    raise RuntimeError(
        f"{url} is not in the local weights cache ({path}) and this "
        "environment has no network egress — place the file there "
        "manually, or construct the model with pretrained=False")
