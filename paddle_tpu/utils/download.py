"""paddle.utils.download (parity: python/paddle/utils/download.py —
get_weights_path_from_url with a local cache).  This environment has
zero egress, so the cache is normally the only source: a URL whose file
is already cached resolves; anything else raises with a clear message
instead of hanging on a socket.

A caller that *does* have a transport can pass ``fetcher`` (a callable
``url -> bytes``); fetches then run through bounded retries with
exponential backoff (FLAGS_download_retries /
FLAGS_download_backoff_base), each attempt passing the
``download.fetch`` chaos point so flaky-mirror behavior is provable in
the fault-injection suite.  The fetched file lands in the cache via a
crash-safe tmp+rename write.
"""
from __future__ import annotations

import hashlib
import os
import time
from typing import Callable, Optional

__all__ = ["get_weights_path_from_url", "fetch_with_retry"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/hapi/weights")


def _map_path(url: str) -> str:
    fname = os.path.basename(url.split("?")[0]) or \
        hashlib.md5(url.encode()).hexdigest()
    return os.path.join(WEIGHTS_HOME, fname)


def fetch_with_retry(fetcher: Callable[[str], bytes], url: str, path: str,
                     retries: Optional[int] = None,
                     backoff_base: Optional[float] = None,
                     md5sum: Optional[str] = None) -> str:
    """Run ``fetcher(url)`` with bounded retries + exponential backoff
    (``sleep(backoff_base * 2^attempt)`` between attempts) and commit the
    bytes to ``path`` atomically.  Transport-shaped failures (OSError,
    ConnectionError — which includes injected ``download.fetch`` chaos)
    and md5 mismatches of the *fetched* bytes retry; anything else
    propagates immediately.  The md5 check runs BEFORE the cache commit,
    so a corrupt fetch can never poison the cache."""
    from paddle_tpu.framework import chaos
    from paddle_tpu.framework.flags import flag
    retries = int(flag("download_retries")) if retries is None \
        else int(retries)
    backoff_base = float(flag("download_backoff_base")) \
        if backoff_base is None else float(backoff_base)
    last: Optional[Exception] = None
    for attempt in range(max(1, retries)):
        try:
            chaos.fault_point("download.fetch", meta={"url": url,
                                                      "attempt": attempt})
            data = bytes(fetcher(url))
            if md5sum and hashlib.md5(data).hexdigest() != md5sum:
                raise ConnectionError(
                    f"fetched bytes for {url} fail the md5 check "
                    "(corrupt/truncated transfer)")
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            from paddle_tpu.distributed.fleet.utils.fs import LocalFS
            LocalFS().atomic_write(path, data)
            return path
        except (ConnectionError, OSError) as e:
            last = e
            if attempt < retries - 1:
                time.sleep(backoff_base * (2 ** attempt))
    raise RuntimeError(
        f"download of {url} failed after {retries} attempts: {last!r}")


def _md5_of(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.md5(f.read()).hexdigest()


def get_weights_path_from_url(url: str, md5sum: str = None,
                              fetcher: Optional[Callable[[str], bytes]]
                              = None) -> str:
    path = _map_path(url)
    if (fetcher is not None and md5sum and os.path.exists(path)
            and _md5_of(path) != md5sum):
        # stale/corrupt cache entry with a live transport: refetch rather
        # than failing forever on the poisoned file
        os.remove(path)
    if not os.path.exists(path) and fetcher is not None:
        # the fetch path verified md5 on the in-memory bytes before the
        # cache commit — no need to re-read the file to check it again
        return fetch_with_retry(fetcher, url, path, md5sum=md5sum)
    if os.path.exists(path):
        if md5sum and _md5_of(path) != md5sum:
            raise IOError(f"cached file {path} fails its md5 check")
        return path
    raise RuntimeError(
        f"{url} is not in the local weights cache ({path}) and this "
        "environment has no network egress — place the file there "
        "manually (or pass fetcher=), or construct the model with "
        "pretrained=False")
