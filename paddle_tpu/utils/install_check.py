"""paddle.utils.run_check (parity: python/paddle/utils/install_check.py
— trains a tiny linear model to verify the install, then reports which
device tier is active)."""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    """Train a 2-step linear regression on the default device; prints the
    same style of success message the reference does."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    print("Running verify PaddlePaddle(TPU) program ... ")
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    y = paddle.to_tensor(np.random.rand(8, 2).astype("float32"))
    for _ in range(2):
        loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert np.isfinite(float(loss)), "install check produced non-finite loss"
    dev = paddle.get_device()
    print(f"PaddlePaddle(TPU) works well on 1 {dev.split(':')[0]}.")
    print("PaddlePaddle(TPU) is installed successfully! Let's start deep "
          "learning with PaddlePaddle(TPU) now.")
    return True
