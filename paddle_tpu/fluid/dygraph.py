"""``fluid.dygraph`` compat (reference: python/paddle/fluid/dygraph/ —
the 1.x imperative surface 2.0 scripts still import)."""
from __future__ import annotations

import contextlib

import numpy as np

import paddle_tpu as _p
from paddle_tpu.core import Tensor
from paddle_tpu.nn import Layer, LayerList, ParameterList, Sequential
from paddle_tpu.autograd import no_grad  # noqa: F401
from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401

__all__ = ["Layer", "LayerList", "ParameterList", "Sequential",
           "to_variable", "guard", "enabled", "enable_dygraph",
           "disable_dygraph", "no_grad", "DataParallel", "grad"]


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """1.x name for to_tensor."""
    t = _p.to_tensor(np.asarray(value))
    if dtype is not None:
        t = t.astype(dtype)
    return t


@contextlib.contextmanager
def guard(place=None):
    """Dygraph IS the default execution model here — the guard is a
    documented no-op kept so 1.x scripts run unchanged."""
    yield


def enabled() -> bool:
    return True


def enable_dygraph(place=None):
    return None


def disable_dygraph():
    raise RuntimeError(
        "static-graph mode does not exist in the TPU-native runtime; "
        "capture with paddle_tpu.jit instead (MIGRATING.md)")


def grad(*args, **kwargs):
    return _p.grad(*args, **kwargs)
