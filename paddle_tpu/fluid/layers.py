"""``fluid.layers`` — the 1.x functional surface, mapped onto the 2.x
API (reference: python/paddle/fluid/layers/{nn,tensor,ops,control_flow,
loss,sequence_lod,detection}.py, ~35k LoC of op wrappers).

Two tiers, by design:
  * value→value functions (elementwise/reduce/activation/shape/loss/
    comparison/control-flow/detection/sequence) map 1:1 onto
    paddle_tpu's functional API with their fluid-era signatures and
    quirks (``act=`` strings, ``axis=-1`` broadcast arg, 1.x argument
    orders) — they run eagerly AND under jit capture like everything
    else.
  * parameter-creating graph builders (fc, embedding, conv2d,
    batch_norm, ...) were static-graph ops that minted persistable
    Variables inside a Program; there is no Program here, so they raise
    with the nn.Layer replacement named.  Unknown names raise
    AttributeError with the same guidance (module __getattr__).
"""
from __future__ import annotations

from functools import partial as _partial

import numpy as _np

import paddle_tpu as _p
import paddle_tpu.nn.functional as _F
from paddle_tpu import static as _static
from paddle_tpu import tensor as _tensor
from paddle_tpu import vision as _vision
from paddle_tpu.core import Tensor as _T

# -- activations / elementwise math (fluid/layers/ops.py) -------------------

abs = _tensor.abs                               # noqa: A001
exp = _tensor.exp
log = _tensor.log
sqrt = _tensor.sqrt
rsqrt = _tensor.rsqrt
square = _tensor.square
floor = _tensor.floor
ceil = _tensor.ceil
round = _tensor.round                           # noqa: A001
sin = _tensor.sin
cos = _tensor.cos
tanh = _tensor.tanh
sigmoid = _F.sigmoid
logsigmoid = _F.log_sigmoid
relu = _F.relu
relu6 = _F.relu6
leaky_relu = _F.leaky_relu
elu = _F.elu
selu = _F.selu
gelu = _F.gelu
hard_sigmoid = _F.hardsigmoid
hard_swish = _F.hardswish
swish = _F.swish
softplus = _F.softplus
softsign = _F.softsign
softshrink = _F.softshrink
maxout = _F.maxout
prelu = _F.prelu
reciprocal = _tensor.reciprocal
softmax = _F.softmax
log_softmax = _F.log_softmax
erf = _tensor.erf
pow = _tensor.pow                               # noqa: A001
sign = _tensor.sign
clip = _tensor.clip


def clip_by_norm(x, max_norm, name=None):
    """clip_by_norm_op: scale x down so its L2 norm is at most max_norm."""
    norm = _tensor.sqrt(_tensor.sum(_tensor.square(x)))
    factor = _tensor.clip(max_norm / _tensor.maximum(
        norm, _p.to_tensor(1e-12)), max=1.0)
    return x * factor


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return _apply_act(out, act)


def _apply_act(out, act):
    if act is None:
        return out
    fn = {"relu": _F.relu, "sigmoid": _F.sigmoid, "tanh": _tensor.tanh,
          "softmax": _F.softmax, "gelu": _F.gelu,
          "leaky_relu": _F.leaky_relu}.get(act)
    if fn is None:
        raise ValueError(f"unsupported act {act!r}")
    return fn(out)


# -- elementwise binary (fluid's axis-broadcast wrappers) -------------------

def _elementwise(op, x, y, axis=-1, act=None, name=None):
    if axis != -1 and getattr(y, "ndim", 0) < getattr(x, "ndim", 0):
        # fluid's axis arg: align y's dims starting at ``axis``
        import paddle_tpu.tensor.manipulation as _m
        extra = x.ndim - axis - y.ndim
        for _ in range(max(extra, 0)):
            y = _m.unsqueeze(y, -1)
    return _apply_act(op(x, y), act)


elementwise_add = _partial(_elementwise, _tensor.add)
elementwise_sub = _partial(_elementwise, _tensor.subtract)
elementwise_mul = _partial(_elementwise, _tensor.multiply)
elementwise_div = _partial(_elementwise, _tensor.divide)
elementwise_min = _partial(_elementwise, _tensor.minimum)
elementwise_max = _partial(_elementwise, _tensor.maximum)
elementwise_mod = _partial(_elementwise, _tensor.remainder)
elementwise_floordiv = _partial(_elementwise, _tensor.floor_divide)
elementwise_pow = _partial(_elementwise, _tensor.pow)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """mul_op: flatten x to 2-D at x_num_col_dims, y likewise, matmul."""
    xs = x.reshape([int(_np.prod(x.shape[:x_num_col_dims])), -1])
    ys = y.reshape([int(_np.prod(y.shape[:y_num_col_dims])), -1])
    return _tensor.matmul(xs, ys)


matmul = _tensor.matmul
bmm = _tensor.bmm
dot = _tensor.dot
addmm = _tensor.addmm if hasattr(_tensor, "addmm") else None


# -- reductions (fluid dim= names) ------------------------------------------

def _reduce(fn, input, dim=None, keep_dim=False, name=None):
    return fn(input, axis=dim, keepdim=keep_dim)


reduce_sum = _partial(_reduce, _tensor.sum)
reduce_mean = _partial(_reduce, _tensor.mean)
reduce_max = _partial(_reduce, _tensor.max)
reduce_min = _partial(_reduce, _tensor.min)
reduce_prod = _partial(_reduce, _tensor.prod)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _tensor.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _tensor.any(input, axis=dim, keepdim=keep_dim)


mean = _tensor.mean
sums = lambda input, out=None: _tensor.add_n(input)      # noqa: E731
sum = _tensor.add_n                                       # noqa: A001
logsumexp = _tensor.logsumexp


# -- tensor creation / shape (fluid/layers/tensor.py) -----------------------

fill_constant = _p.fill_constant
zeros = lambda shape, dtype="float32", force_cpu=False: _tensor.zeros(  # noqa: E731
    shape, dtype=dtype)
ones = lambda shape, dtype="float32", force_cpu=False: _tensor.ones(  # noqa: E731
    shape, dtype=dtype)
zeros_like = _tensor.zeros_like
ones_like = _tensor.ones_like
full_like = _tensor.full_like
linspace = _tensor.linspace
range = _tensor.arange                          # noqa: A001
arange = _tensor.arange
assign = lambda input, output=None: _T(_np.asarray(  # noqa: E731
    input.numpy() if isinstance(input, _T) else input))
cast = _tensor.cast
concat = _tensor.concat
stack = _tensor.stack
unstack = _tensor.unstack
split = _tensor.split
reshape = _tensor.reshape
transpose = _tensor.transpose
flatten = _tensor.flatten
squeeze = _tensor.squeeze
unsqueeze = _tensor.unsqueeze
expand = _tensor.expand
expand_as = _tensor.expand_as
tile = _tensor.tile
slice = _tensor.slice                           # noqa: A001
strided_slice = _tensor.strided_slice
gather = _tensor.gather
gather_nd = _tensor.gather_nd
scatter = _tensor.scatter
scatter_nd_add = _tensor.scatter_nd_add
shard_index = _tensor.shard_index if hasattr(_tensor, "shard_index") \
    else None
where = _tensor.where
argmax = _tensor.argmax
argmin = _tensor.argmin
argsort = lambda input, axis=-1, descending=False, name=None: (  # noqa: E731
    _tensor.sort(input, axis=axis, descending=descending),
    _tensor.argsort(input, axis=axis, descending=descending))
topk = _tensor.topk
unique = _tensor.unique
shape = _p.shape
rank = _p.rank
increment = lambda x, value=1.0, in_place=True: _p.increment(  # noqa: E731
    x, value) if hasattr(_p, "increment") else x.add_(value)
one_hot = lambda input, depth, allow_out_of_range=False: _F.one_hot(  # noqa: E731
    input, depth)
diag = _tensor.diag
eye = _tensor.eye
cumsum = _tensor.cumsum
crop_tensor = _tensor.crop
pad = _F.pad
pad2d = _F.pad2d if hasattr(_F, "pad2d") else _F.pad
meshgrid = _tensor.meshgrid
roll = _tensor.roll
flip = _tensor.flip
reverse = _tensor.flip


# -- comparison (fluid/layers/control_flow.py + compare ops) ----------------

equal = _tensor.equal
not_equal = _tensor.not_equal
greater_than = _tensor.greater_than
greater_equal = _tensor.greater_equal
less_than = _tensor.less_than
less_equal = _tensor.less_equal
logical_and = _tensor.logical_and
logical_or = _tensor.logical_or
logical_not = _tensor.logical_not
logical_xor = _tensor.logical_xor
isfinite = _tensor.isfinite
has_nan = _p.has_nan
has_inf = _p.has_inf


# -- control flow (dual-regime, static/nn.py) -------------------------------

cond = _static.nn.cond
case = _static.nn.case
switch_case = _static.nn.switch_case
while_loop = _static.nn.while_loop


# -- losses (fluid/layers/loss.py) ------------------------------------------

def cross_entropy(input, label, soft_label=False, ignore_index=-100,
                  name=None):
    """fluid semantics: ``input`` is POST-softmax probabilities and the
    result keeps the per-example shape (no mean)."""
    eps = 1e-12
    if soft_label:
        return -_tensor.sum(label * _tensor.log(input + eps), axis=-1,
                            keepdim=True)
    g = _tensor.gather_nd(
        input, _tensor.stack(
            [_tensor.arange(0, int(input.shape[0]), dtype="int64"),
             label.reshape([-1]).astype("int64")], axis=1))
    return -_tensor.log(g + eps).reshape([-1, 1])


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = _F.cross_entropy(logits, label, soft_label=soft_label,
                            ignore_index=ignore_index, reduction="none",
                            axis=axis)
    loss = _tensor.unsqueeze(loss, -1)
    if return_softmax:
        return loss, _F.softmax(logits, axis=axis)
    return loss


def square_error_cost(input, label):
    return _tensor.square(input - label)


def mse_loss(input, label):
    return _F.mse_loss(input, label)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0):
    diff = x - y
    if inside_weight is not None:
        diff = diff * inside_weight
    sigma2 = sigma * sigma
    ad = _tensor.abs(diff)
    small = _tensor.cast(ad < (1.0 / sigma2), "float32")
    loss = small * 0.5 * sigma2 * _tensor.square(diff) + \
        (1.0 - small) * (ad - 0.5 / sigma2)
    if outside_weight is not None:
        loss = loss * outside_weight
    return _tensor.sum(loss, axis=-1, keepdim=True)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False, name=None):
    loss = _F.binary_cross_entropy_with_logits(x, label, reduction="none")
    if normalize:
        n = _tensor.sum(_tensor.cast(label != ignore_index, "float32"))
        loss = loss / _tensor.maximum(n, _p.to_tensor(1.0))
    return loss


def huber_loss(input, label, delta):
    return _F.smooth_l1_loss(input, label, reduction="none", delta=delta)


def kldiv_loss(x, target, reduction="mean", name=None):
    return _F.kl_div(x, target, reduction=reduction)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    C = int(label.shape[-1])
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / C


def accuracy(input, label, k=1, correct=None, total=None):
    from paddle_tpu.metric import accuracy as _acc
    return _acc(input, label, k=k)


# -- interpolation / vision (fluid/layers/nn.py tail) -----------------------

def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="bilinear", align_corners=align_corners,
                          data_format=data_format)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    return _F.interpolate(input, size=out_shape, scale_factor=scale,
                          mode="nearest", data_format=data_format)


grid_sampler = _F.grid_sample
affine_grid = _F.affine_grid
image_resize = resize_bilinear

# detection surface re-export (fluid/layers/detection.py)
yolo_box = _vision.ops.yolo_box
yolov3_loss = _vision.ops.yolo_loss
prior_box = _vision.ops.prior_box
density_prior_box = _vision.ops.density_prior_box
anchor_generator = _vision.ops.anchor_generator
box_coder = _vision.ops.box_coder
box_clip = _vision.ops.box_clip
iou_similarity = _vision.ops.iou_similarity
bipartite_match = _vision.ops.bipartite_match
target_assign = _vision.ops.target_assign
multiclass_nms = _vision.ops.multiclass_nms
matrix_nms = _vision.ops.matrix_nms
locality_aware_nms = _vision.ops.locality_aware_nms
distribute_fpn_proposals = _vision.ops.distribute_fpn_proposals
collect_fpn_proposals = _vision.ops.collect_fpn_proposals
generate_proposals = _vision.ops.generate_proposals
generate_proposal_labels = _vision.ops.generate_proposal_labels
generate_mask_labels = _vision.ops.generate_mask_labels
rpn_target_assign = _vision.ops.rpn_target_assign
retinanet_target_assign = _vision.ops.retinanet_target_assign
retinanet_detection_output = _vision.ops.retinanet_detection_output
sigmoid_focal_loss = _vision.ops.sigmoid_focal_loss
roi_align = _vision.ops.roi_align
roi_pool = _vision.ops.roi_pool
roi_perspective_transform = _vision.ops.roi_perspective_transform
polygon_box_transform = _vision.ops.polygon_box_transform
box_decoder_and_assign = _vision.ops.box_decoder_and_assign
mine_hard_examples = _vision.ops.mine_hard_examples


# -- sequence ops (tensor/sequence.py ragged encodings) ---------------------

def _seq(name):
    import paddle_tpu.tensor.sequence as _s
    return getattr(_s, name, None)


sequence_pad = _seq("sequence_pad")
sequence_unpad = _seq("sequence_unpad")
sequence_mask = _seq("sequence_mask")
sequence_pool = _seq("sequence_pool")
sequence_expand = _seq("sequence_expand")
sequence_softmax = _seq("sequence_softmax")
sequence_reverse = _seq("sequence_reverse")
sequence_concat = _seq("sequence_concat")


# -- dropout / norm functionals ---------------------------------------------

def dropout(x, dropout_prob, is_test=None, seed=None,
            dropout_implementation="downgrade_in_infer", name=None):
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return _F.dropout(x, p=dropout_prob,
                      training=(not is_test) if is_test is not None
                      else True, mode=mode)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return _F.normalize(x, p=2, axis=axis, epsilon=epsilon)


# -- parameter-creating graph builders: raise with the replacement ----------

_STATIC_BUILDERS = {
    "fc": "nn.Linear",
    "embedding": "nn.Embedding",
    "conv2d": "nn.Conv2D",
    "conv3d": "nn.Conv3D",
    "conv2d_transpose": "nn.Conv2DTranspose",
    "batch_norm": "nn.BatchNorm2D",
    "instance_norm": "nn.InstanceNorm2D",
    "layer_norm": "nn.LayerNorm",
    "group_norm": "nn.GroupNorm",
    "pool2d": "nn.MaxPool2D / nn.AvgPool2D",
    "pool3d": "nn.MaxPool3D / nn.AvgPool3D",
    "data": "plain function arguments (trace captures shapes)",
    "create_parameter": "paddle_tpu.nn.Layer.create_parameter",
    "nce": "paddle_tpu.nn.functional.nce",
    "hsigmoid": "paddle_tpu.nn.functional.hsigmoid_loss",
    "lstm": "nn.LSTM",
    "gru_unit": "nn.GRUCell",
    "dynamic_lstm": "nn.LSTM",
    "dynamic_gru": "nn.GRU",
    "crf_decoding": "paddle_tpu.text (ViterbiDecoder)",
    "linear_chain_crf": "paddle_tpu.text (LinearChainCrf role)",
}


def _make_builder_stub(name, repl):
    def stub(*a, **k):
        raise RuntimeError(
            f"fluid.layers.{name} was a static-graph op that created "
            f"persistable parameters inside a Program; the TPU-native "
            f"equivalent is {repl} (see MIGRATING.md).")
    stub.__name__ = name
    return stub


for _name, _repl in _STATIC_BUILDERS.items():
    if _name not in globals() or globals()[_name] is None:
        globals()[_name] = _make_builder_stub(_name, _repl)


def __getattr__(name):
    raise AttributeError(
        f"fluid.layers.{name} is not in the compat surface; the 2.x API "
        f"(paddle_tpu.nn/functional/tensor) is the supported path — see "
        f"MIGRATING.md for the mapping table.")
