"""``paddle.fluid`` compat namespace (the 1.x/2.0-era import surface).

Reference: python/paddle/fluid/__init__.py — the pre-2.0 API root that
2.0-era scripts still import for ``fluid.layers``, ``fluid.dygraph``,
``fluid.io`` and the Place/ParamAttr types.

Scope (same design as ``paddle_tpu.static``): everything that operates
on *values* maps directly onto the 2.x functional surface; the
static-graph *program builders* (Program/Executor/scopes and the
param-creating layers like ``layers.fc``) raise with a pointer to the
TPU-native replacement — a documented decision, not an accident
(SURVEY.md: ProgramDesc/executors are n/a-by-design under XLA).
"""
from __future__ import annotations

import paddle_tpu as _paddle
from paddle_tpu.core import (CPUPlace, CUDAPinnedPlace, CUDAPlace,
                             TPUPlace, Tensor)
from paddle_tpu.nn.layer.common import ParamAttr
from paddle_tpu import regularizer  # noqa: F401
from paddle_tpu.framework import io as _fio

from paddle_tpu.fluid import layers  # noqa: E402,F401
from paddle_tpu.fluid import dygraph  # noqa: E402,F401
from paddle_tpu.fluid import initializer  # noqa: E402,F401
from paddle_tpu.fluid import io  # noqa: E402,F401
from paddle_tpu.fluid import optimizer  # noqa: E402,F401

__all__ = ["layers", "dygraph", "initializer", "io", "optimizer",
           "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "TPUPlace",
           "ParamAttr", "LoDTensor", "core", "default_main_program",
           "default_startup_program", "Program", "Executor",
           "program_guard", "regularizer"]

LoDTensor = Tensor


class _Core:
    """Minimal ``fluid.core`` stand-in (VarDesc dtype enum + Places)."""

    CPUPlace = CPUPlace
    CUDAPlace = CUDAPlace
    CUDAPinnedPlace = CUDAPinnedPlace

    class VarDesc:
        class VarType:
            FP16 = "float16"
            BF16 = "bfloat16"
            FP32 = "float32"
            FP64 = "float64"
            INT8 = "int8"
            INT16 = "int16"
            INT32 = "int32"
            INT64 = "int64"
            BOOL = "bool"


core = _Core()


def _static_only(name):
    raise RuntimeError(
        f"fluid.{name} is static-graph machinery the TPU-native runtime "
        f"replaces: capture with paddle_tpu.jit (to_static/TrainStep) "
        f"instead — see MIGRATING.md ('Static graph').")


def default_main_program():
    _static_only("default_main_program")


def default_startup_program():
    _static_only("default_startup_program")


def program_guard(*a, **k):
    _static_only("program_guard")


from paddle_tpu.static import Executor, Program  # noqa: E402,F401

save = _fio.save
load = _fio.load


def is_compiled_with_cuda():
    return False
