"""``fluid.initializer`` compat — 1.x initializer class names mapped to
the 2.x nn.initializer surface (reference:
python/paddle/fluid/initializer.py)."""
from paddle_tpu.nn.initializer import (Assign, Constant, KaimingNormal,
                                       KaimingUniform, Normal,
                                       TruncatedNormal, Uniform,
                                       XavierNormal, XavierUniform)

__all__ = ["Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
           "MSRA", "NumpyArrayInitializer",
           "ConstantInitializer", "UniformInitializer",
           "NormalInitializer", "XavierInitializer", "MSRAInitializer"]

# 1.x aliases (fluid exported both Foo and FooInitializer)
Xavier = XavierNormal
MSRA = KaimingNormal
NumpyArrayInitializer = Assign
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
TruncatedNormalInitializer = TruncatedNormal
KaimingUniformInitializer = KaimingUniform
