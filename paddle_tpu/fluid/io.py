"""``fluid.io`` compat (reference: python/paddle/fluid/io.py — 1.x
save/load + the reader decorators that predate DataLoader)."""
from __future__ import annotations

from paddle_tpu.framework.io import load, save  # noqa: F401
from paddle_tpu.reader import (buffered, cache, chain, compose, firstn,
                               map_readers, shuffle, xmap_readers)  # noqa: F401
from paddle_tpu.io import DataLoader  # noqa: F401
from paddle_tpu.static import (load_inference_model,
                               save_inference_model)  # noqa: F401

__all__ = ["save", "load", "save_inference_model", "load_inference_model",
           "DataLoader", "shuffle", "buffered", "cache", "chain",
           "compose", "firstn", "map_readers", "xmap_readers",
           "save_params", "load_params", "save_persistables",
           "load_persistables"]


def _params_of(program_or_layer):
    params = getattr(program_or_layer, "state_dict", None)
    if params is None:
        raise RuntimeError(
            "fluid.io.save_params/load_params take a Layer here (there is "
            "no Program); pass the model (MIGRATING.md)")
    return program_or_layer


def save_params(executor, dirname, main_program=None, filename=None):
    """1.x signature kept; ``main_program`` slot takes the Layer."""
    model = _params_of(main_program if main_program is not None
                       else executor)
    save(model.state_dict(), f"{dirname}/{filename or 'params'}.pdparams")


def load_params(executor, dirname, main_program=None, filename=None):
    model = _params_of(main_program if main_program is not None
                       else executor)
    model.set_state_dict(
        load(f"{dirname}/{filename or 'params'}.pdparams"))


save_persistables = save_params
load_persistables = load_params
