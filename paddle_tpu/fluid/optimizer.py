"""``fluid.optimizer`` compat — 1.x optimizer class names and their
``parameter_list``/``regularization`` keyword spellings (reference:
python/paddle/fluid/optimizer.py)."""
from __future__ import annotations

from paddle_tpu import optimizer as _opt

__all__ = ["SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
           "Adagrad", "AdagradOptimizer", "Adam", "AdamOptimizer",
           "Adamax", "AdamaxOptimizer", "RMSProp", "RMSPropOptimizer",
           "Lamb", "LambOptimizer"]


def _fluidify(cls):
    """Accept the 1.x keyword spellings on a 2.x optimizer class."""

    class Fluid(cls):
        def __init__(self, learning_rate=0.001, parameter_list=None,
                     regularization=None, grad_clip=None, name=None,
                     **kw):
            kw.setdefault("parameters", parameter_list)
            kw.setdefault("weight_decay", regularization)
            kw.pop("name", None)
            super().__init__(learning_rate=learning_rate,
                             grad_clip=grad_clip, **kw)

        def minimize(self, loss, startup_program=None, parameter_list=None,
                     no_grad_set=None):
            """1.x loop: backward + apply in one call."""
            loss.backward()
            self.step()
            self.clear_grad()
            return [], []

    Fluid.__name__ = cls.__name__
    Fluid.__qualname__ = cls.__name__
    return Fluid


SGD = SGDOptimizer = _fluidify(_opt.SGD)
Momentum = MomentumOptimizer = _fluidify(_opt.Momentum)
Adagrad = AdagradOptimizer = _fluidify(_opt.Adagrad)
Adam = AdamOptimizer = _fluidify(_opt.Adam)
Adamax = AdamaxOptimizer = _fluidify(_opt.Adamax)
RMSProp = RMSPropOptimizer = _fluidify(_opt.RMSProp)
Lamb = LambOptimizer = _fluidify(_opt.Lamb)
