"""Differential oracle for Pallas kernels — the runtime half of the
PTA6xx static passes (framework/analysis/pallas_kernels.py).

A kernel that compiles is not a kernel that is right: Mosaic clips
out-of-bounds writes and pads out-of-bounds reads, so a tiling bug
produces silently wrong numbers, not a fault.  The oracle closes the
loop the way the parity probe does for replica state: run the SAME
kernel callable three ways —

* compiled (whatever path the dispatcher picks on this backend),
* ``interpret=True`` (the Pallas interpreter, exact block semantics),
* the pure-jnp reference (ground truth),

and gate tolerance agreement per output leaf.  A disagreement names the
first divergent operand with the SAME ``<name>.<operand>`` label the
static pass prints (see ``pallas_kernels.operand_labels``), so a static
PTA601 finding and a runtime ``PALLAS_DIVERGENCE`` line point at one
name.

Armed via ``FLAGS_pallas_verify`` (also armed per tiling candidate by
``tools/flash_autotune.py`` before any candidate is timed).  Disarmed
is one flag lookup — the callables are not even invoked.  The oracle
NEVER raises: the ``pallas.verify`` chaos point plus swallow-and-count
(``pallas_verify_errors_total``) keep the watcher from crashing the
watched (``tools/chaos_drill.py`` discipline).

Metrics: ``pallas_verify_checks_total``, ``pallas_divergence_total``,
``pallas_verify_errors_total``; divergences additionally record a
``pallas.divergence`` flight event carrying the operand label and the
max abs error.
"""
from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.framework import chaos, monitor
from paddle_tpu.framework.flags import flag

__all__ = ["armed", "verify_call", "interpreted", "boundary_corpus",
           "check_flash_candidate", "VerifyResult"]

monitor.describe("pallas_verify_checks_total",
                 "differential-oracle checks completed (armed only)")
monitor.describe("pallas_divergence_total",
                 "kernel outputs that disagreed between the compiled/"
                 "interpret/reference legs; the pallas.divergence "
                 "flight event names the operand")
monitor.describe("pallas_verify_errors_total",
                 "oracle faults (real or pallas.verify chaos) swallowed "
                 "without touching the watched kernel call")

# (mode label, mode label) pairs compared by verify_call; kept as data so
# the report names which legs disagreed
_LEGS = ("compiled", "interpret", "reference")


def armed() -> bool:
    """One flag lookup — the entire disarmed cost of the oracle."""
    try:
        return bool(flag("pallas_verify"))
    except Exception:                  # noqa: BLE001 — flags not initialised
        return False


@contextlib.contextmanager
def interpreted(*modules):
    """Flip each kernel module's ``_INTERPRET`` toggle for the scope —
    the same switch the interpret-mode tests use."""
    saved = [getattr(m, "_INTERPRET", False) for m in modules]
    for m in modules:
        m._INTERPRET = True
    try:
        yield
    finally:
        for m, s in zip(modules, saved):
            m._INTERPRET = s


@dataclass
class VerifyResult:
    """Outcome of one oracle check.  ``divergent`` is True when any
    output leaf disagrees between any two legs; ``operand`` then names
    the first divergent leaf with the static pass's label."""
    name: str
    divergent: bool = False
    operand: Optional[str] = None
    legs: Optional[Tuple[str, str]] = None
    max_abs_err: float = 0.0
    checked: int = 0
    labels: List[str] = field(default_factory=list)


def _leaves(out) -> List[Any]:
    import jax
    return [x for x in jax.tree_util.tree_leaves(out)
            if hasattr(x, "shape")]


def _labels_for(name: str, run_kernel, args, n_out: int,
                out_labels) -> List[str]:
    if out_labels:
        return list(out_labels)
    # derive from the kernel model so the runtime label matches the
    # static pass exactly (single-pallas_call kernels; others fall back
    # to positional labels)
    try:
        from paddle_tpu.framework.analysis.pallas_kernels import (
            trace_kernels)
        models = trace_kernels(run_kernel, *args)
        if len(models) == 1 and len(models[0].outputs) == n_out:
            return [f"{name}.{op.label}" for op in models[0].outputs]
    except Exception:                  # noqa: BLE001 — labels are best-effort
        pass
    return [f"{name}.out{i}" for i in range(n_out)]


def _compare(name: str, outs: List[Tuple[str, List[Any]]],
             labels: List[str], rtol: float,
             atol: float) -> VerifyResult:
    res = VerifyResult(name=name, labels=labels)
    for i in range(min(len(o) for _, o in outs)):
        res.checked += 1
        for (la, oa), (lb, ob) in zip(outs, outs[1:]):
            a = np.asarray(oa[i], dtype=np.float64)
            b = np.asarray(ob[i], dtype=np.float64)
            ok = a.shape == b.shape and bool(
                np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=False))
            if ok:
                continue
            if a.shape != b.shape:
                err = float("inf")
            else:
                diff = np.abs(a - b)
                finite = diff[~np.isnan(diff)]
                err = float(finite.max()) if finite.size else float("nan")
            res.divergent = True
            res.operand = labels[i] if i < len(labels) else \
                f"{name}.out{i}"
            res.legs = (la, lb)
            res.max_abs_err = max(res.max_abs_err, err)
            return res
    return res


def verify_call(name: str, run_kernel: Callable, run_reference:
                Optional[Callable], args: Sequence[Any] = (), *,
                interpret_modules: Sequence[Any] = (),
                out_labels: Optional[Sequence[str]] = None,
                skip_compiled: bool = False,
                rtol: float = 1e-4,
                atol: float = 1e-5) -> Optional[VerifyResult]:
    """Run the differential oracle on one kernel call site.

    ``run_kernel(*args)`` is the kernel exactly as the caller would run
    it; ``run_reference(*args)`` the pure-jnp ground truth (None skips
    that leg).  ``interpret_modules`` are the kernel modules whose
    ``_INTERPRET`` toggle selects the interpreter leg (empty skips it).
    ``skip_compiled`` drops the compiled leg — the CPU configuration,
    where Mosaic cannot lower and only interpret-vs-reference is
    meaningful.

    Disarmed (``FLAGS_pallas_verify`` false): returns None WITHOUT
    invoking any callable — the cost is one flag lookup.  Armed: never
    raises; a broken oracle (real or injected via the ``pallas.verify``
    chaos point) is swallowed and counted
    (``pallas_verify_errors_total``), the caller's own kernel call is
    untouched.
    """
    if not armed():
        return None
    from paddle_tpu.framework.observability import flight
    try:
        chaos.fault_point("pallas.verify", meta={"name": name})
        outs: List[Tuple[str, List[Any]]] = []
        if not skip_compiled:
            outs.append(("compiled", _leaves(run_kernel(*args))))
        if interpret_modules:
            with interpreted(*interpret_modules):
                outs.append(("interpret", _leaves(run_kernel(*args))))
        if run_reference is not None:
            outs.append(("reference", _leaves(run_reference(*args))))
        if len(outs) < 2:
            return None
        labels = _labels_for(name, run_kernel, args,
                             len(outs[0][1]), out_labels)
        res = _compare(name, outs, labels, rtol, atol)
    except Exception:                  # noqa: BLE001 — swallow-and-count
        monitor.stat_add("pallas_verify_errors_total")
        return None
    monitor.stat_add("pallas_verify_checks_total")
    if res.divergent:
        monitor.stat_add("pallas_divergence_total")
        flight.record("pallas.divergence", severity="error",
                      name=name, operand=res.operand,
                      legs=list(res.legs or ()),
                      max_abs_err=res.max_abs_err)
    return res


def boundary_corpus(block_q: int = 128, block_k: int = 128,
                    d: int = 64) -> List[dict]:
    """The deterministic boundary-shape corpus the autotune oracle
    sweeps per tiling candidate: non-divisible lengths (tail blocks on
    both grid axes), the single-block case, a zero-tail case, and the
    dtype matrix.  Pure function of the block shape — same candidate,
    same corpus, same verdict."""
    bq, bk = int(block_q), int(block_k)
    shapes = [
        # (sq, sk): non-divisible tails on q, on k, on both, single block
        (bq + bq // 2, bk + bk // 2),
        (bq, bk + 1),
        (bq + 1, bk),
        (bq, bk),
    ]
    corpus = []
    for dtype in ("float32", "bfloat16"):
        for sq, sk in shapes:
            corpus.append({"sq": int(sq), "sk": int(sk), "d": int(d),
                           "dtype": dtype})
    return corpus


def check_flash_candidate(block_q, block_k, *, d=64, dtype="bfloat16",
                          causal=False, biased=False, heads=2,
                          grads=True):
    """Validate one flash-attention tiling candidate on the boundary
    corpus (flash_autotune's pre-timing gate: a fast wrong kernel must
    never win a sweep).

    Each corpus case runs fwd (and, with ``grads``, dq/dk/dv) through
    :func:`verify_call` — compiled vs interpret vs the XLA reference —
    with the candidate blocks forced.  Returns [] when every case
    agrees, else one ``{"sq", "sk", "dtype", "operand"}`` dict per
    divergent case.  Corpus cases the dispatcher would not send to the
    kernel anyway (masked non-divisible shapes, causal sq>sk) are
    skipped, not failed.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import autotune
    from paddle_tpu.ops.pallas import flash_attention as fa

    failures = []
    for case in boundary_corpus(block_q, block_k, d):
        sq, sk, cd = case["sq"], case["sk"], case["d"]
        if causal and sq > sk:
            continue
        if biased and (sq % block_q or sk % block_k):
            continue
        jdt = jnp.bfloat16 if case["dtype"] == "bfloat16" else jnp.float32
        rng = np.random.default_rng(sq * 7919 + sk)
        q = jnp.asarray(rng.standard_normal((1, sq, heads, cd)), jdt)
        k = jnp.asarray(rng.standard_normal((1, sk, heads, cd)), jdt)
        v = jnp.asarray(rng.standard_normal((1, sk, heads, cd)), jdt)
        bias = jnp.asarray(rng.standard_normal((1, 1, 1, sk)),
                           jnp.float32) if biased else None
        scale = 1.0 / float(np.sqrt(cd))

        def _loss(fn, q_, k_, v_):
            return (fn(q_, k_, v_) ** 2).astype(jnp.float32).sum()

        def run_kernel(q_, k_, v_):
            flash = lambda a, b, c: fa.flash_attention(
                a, b, c, causal=causal, scale=scale, bias=bias)
            with autotune.force_blocks(block_q, block_k):
                if not grads:
                    return flash(q_, k_, v_)
                return jax.value_and_grad(
                    lambda a, b, c: _loss(flash, a, b, c),
                    argnums=(0, 1, 2))(q_, k_, v_)

        def run_reference(q_, k_, v_):
            ref = lambda a, b, c: fa._xla_reference(
                a, b, c, scale, causal, bias=bias)
            if not grads:
                return ref(q_, k_, v_)
            return jax.value_and_grad(
                lambda a, b, c: _loss(ref, a, b, c),
                argnums=(0, 1, 2))(q_, k_, v_)

        name = f"flash[{block_q}x{block_k}]"
        labels = [f"{name}.out"] if not grads else \
            [f"{name}.{x}" for x in ("loss", "dq", "dk", "dv")]
        loose = case["dtype"] == "bfloat16"
        res = verify_call(name, run_kernel, run_reference, (q, k, v),
                          interpret_modules=(fa,), out_labels=labels,
                          skip_compiled=not fa._backend_is_tpu(),
                          rtol=5e-2 if loose else 5e-3,
                          atol=5e-2 if loose else 5e-4)
        if res is not None and res.divergent:
            failures.append({"sq": sq, "sk": sk, "dtype": case["dtype"],
                             "operand": res.operand})
    return failures
