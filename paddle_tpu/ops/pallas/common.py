"""Shared Pallas kernel helpers."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def no_x64():
    """Context manager forcing 32-bit trace semantics for a kernel call.

    The package enables jax_enable_x64 globally (paddle parity), but
    Pallas TPU kernels are written for 32-bit refs; ``jax.enable_x64``
    was removed upstream, so route through the experimental manager.
    """
    try:
        from jax.experimental import disable_x64
        return disable_x64()
    except ImportError:
        return jax.enable_x64(False)


def dot_nt(a, b):
    """a (m, d) · b (n, d) → (m, n): contraction over the trailing dim with
    f32 accumulation — keeps bf16 inputs on the MXU's fast path instead of
    casting to f32 first (which quarters MXU throughput on v5e)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
