"""Shared Pallas kernel helpers."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def dot_nt(a, b):
    """a (m, d) · b (n, d) → (m, n): contraction over the trailing dim with
    f32 accumulation — keeps bf16 inputs on the MXU's fast path instead of
    casting to f32 first (which quarters MXU throughput on v5e)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
