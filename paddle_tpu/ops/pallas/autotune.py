"""Measured flash-attention block-size cache.

Round-2 verdict item: BLOCK_Q/K=512 was a config-global compromise (the
256<->512 flip-flop in history shows the answer is shape-dependent).
This cache keys measured winners on (Sq, Sk, head_dim, dtype, causal,
biased):

- ``flash_blocks.json`` next to this file ships pre-measured entries for
  the bench/model configs (regenerate with ``tools/flash_autotune.py``
  on a real chip).
- On a cache miss the kernel uses the BLOCK_Q/BLOCK_K heuristic, unless
  ``FLAGS_flash_autotune`` is set — then candidates are timed on-device
  once (fwd+bwd, value-fetch fenced) and the winner is persisted.

Reference role: the reference hand-tuned per-arch tile sizes inside its
CUDA kernels; on TPU the tile choice is a trace-time knob, so it can be
measured instead of guessed.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Optional, Tuple

_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "flash_blocks.json")
_cache = None
_lock = threading.Lock()

# set via force_blocks() during measurement
_FORCE: Optional[Tuple[int, int]] = None

CANDIDATES = [(256, 256), (256, 512), (512, 256), (512, 512),
              (1024, 512), (512, 1024)]


def _load() -> dict:
    global _cache
    if _cache is None:
        with _lock:
            if _cache is None:
                try:
                    with open(_PATH) as f:
                        _cache = json.load(f)
                except Exception:
                    _cache = {}
    return _cache


def _key(sq, sk, d, dtype, causal, biased) -> str:
    return (f"{sq}x{sk}:d{d}:{dtype}:"
            f"{'causal' if causal else 'full'}:"
            f"{'bias' if biased else 'nobias'}")


def lookup(sq, sk, d, dtype, causal, biased):
    if _FORCE is not None:
        return _FORCE
    hit = _load().get(_key(sq, sk, d, str(dtype), causal, biased))
    return tuple(hit) if hit else None


def record(sq, sk, d, dtype, causal, biased, blocks, persist=True):
    c = _load()
    c[_key(sq, sk, d, str(dtype), causal, biased)] = list(blocks)
    if persist:
        try:
            with _lock, open(_PATH, "w") as f:
                json.dump(c, f, indent=1, sort_keys=True)
        except OSError:
            pass                       # read-only install: in-memory only


class force_blocks:
    """Context manager pinning the kernel block choice (measurement)."""

    def __init__(self, bq: int, bk: int):
        self._blocks = (bq, bk)

    def __enter__(self):
        global _FORCE
        self._prev = _FORCE
        _FORCE = self._blocks
        return self

    def __exit__(self, *exc):
        global _FORCE
        _FORCE = self._prev
        return False


def _fence(x):
    import numpy as np
    np.asarray(x)


def measure(sq, sk, d, dtype="bfloat16", causal=False, biased=False,
            batch=1, heads=8, iters=3, persist=True, verbose=False):
    """Time fwd+bwd per candidate on the current device; record winner."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import time

    from paddle_tpu.ops.pallas import flash_attention as fa

    jdt = jnp.bfloat16 if str(dtype) == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((batch, sq, heads, d)), jdt)
    k = jnp.asarray(rng.standard_normal((batch, sk, heads, d)), jdt)
    v = jnp.asarray(rng.standard_normal((batch, sk, heads, d)), jdt)
    bias = None
    if biased:
        bias = jnp.asarray(
            rng.standard_normal((batch, 1, 1, sk)) * 0.0, jnp.float32)

    def loss(q_, k_, v_):
        out = fa.flash_attention(q_, k_, v_, causal=causal, bias=bias)
        return out.astype(jnp.float32).sum()

    results = {}
    for bq, bk in CANDIDATES:
        if bq > sq or bk > sk or sq % bq or sk % bk:
            continue
        try:
            with force_blocks(bq, bk):
                f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
                val, grads = f(q, k, v)          # compile + warm
                _fence(val)
                t0 = time.perf_counter()
                for _ in range(iters):
                    val, grads = f(q, k, v)
                _fence(val)
                dt = (time.perf_counter() - t0) / iters
            results[(bq, bk)] = dt
            if verbose:
                print(f"  ({bq},{bk}): {dt*1e3:.2f} ms")
        except Exception as e:                   # noqa: BLE001
            if verbose:
                print(f"  ({bq},{bk}): failed {e!r}")
    if not results:
        return None
    best = min(results, key=results.get)
    record(sq, sk, d, dtype, causal, biased, best, persist=persist)
    return best, results
