"""Measured flash-attention block-size cache.

Round-2 verdict item: BLOCK_Q/K=512 was a config-global compromise (the
256<->512 flip-flop in history shows the answer is shape-dependent).
This cache keys measured winners on (Sq, Sk, head_dim, dtype, causal,
biased):

- ``flash_blocks.json`` next to this file ships pre-measured entries for
  the bench/model configs (regenerate with ``tools/flash_autotune.py``
  on a real chip).
- On a cache miss the kernel uses the BLOCK_Q/BLOCK_K heuristic, unless
  ``FLAGS_flash_autotune`` is set — then candidates are timed on-device
  once (fwd+bwd, value-fetch fenced) and the winner is persisted.

Reference role: the reference hand-tuned per-arch tile sizes inside its
CUDA kernels; on TPU the tile choice is a trace-time knob, so it can be
measured instead of guessed.
"""
from __future__ import annotations

import json
import os
import threading
_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "flash_blocks.json")
_cache = None
_lock = threading.Lock()

# set via force_blocks() during measurement; keys "both"/"fwd"/"bwd"
_FORCE: dict = {}

CANDIDATES = [(256, 256), (256, 512), (512, 256), (512, 512),
              (1024, 512), (512, 1024), (1024, 1024)]


def _load() -> dict:
    global _cache
    if _cache is None:
        with _lock:
            if _cache is None:
                try:
                    with open(_PATH) as f:
                        _cache = json.load(f)
                except Exception:
                    _cache = {}
    return _cache


def _key(sq, sk, d, dtype, causal, biased, direction="fwd") -> str:
    base = (f"{sq}x{sk}:d{d}:{dtype}:"
            f"{'causal' if causal else 'full'}:"
            f"{'bias' if biased else 'nobias'}")
    # fwd keeps the historical key so shipped flash_blocks.json entries
    # stay valid; bwd entries are suffixed
    return base if direction == "fwd" else base + ":" + direction


def _entry_blocks(hit):
    """Entry value → (bq, bk).  Entries are either the legacy bare
    ``[bq, bk]`` list or the stamped ``{"blocks": [...], "verified":
    true}`` dict written when the differential oracle validated the
    candidate before it was timed."""
    if isinstance(hit, dict):
        hit = hit.get("blocks")
    return tuple(hit) if hit else None


def lookup(sq, sk, d, dtype, causal, biased, direction="fwd"):
    forced = _FORCE.get(direction, _FORCE.get("both"))
    if forced is not None:
        return forced
    c = _load()
    hit = c.get(_key(sq, sk, d, str(dtype), causal, biased, direction))
    if hit is None and direction != "fwd":
        # fall back to the direction-less (fwd) measurement
        hit = c.get(_key(sq, sk, d, str(dtype), causal, biased))
    return _entry_blocks(hit)


def record(sq, sk, d, dtype, causal, biased, blocks, persist=True,
           direction="fwd", verified=False):
    c = _load()
    entry = {"blocks": list(blocks), "verified": True} if verified \
        else list(blocks)
    c[_key(sq, sk, d, str(dtype), causal, biased, direction)] = entry
    if persist:
        try:
            with _lock, open(_PATH, "w") as f:
                json.dump(c, f, indent=1, sort_keys=True)
        except OSError:
            pass                       # read-only install: in-memory only


class force_blocks:
    """Context manager pinning the kernel block choice (measurement).
    ``direction`` pins only the forward ("fwd") or backward ("bwd")
    kernels; default pins both."""

    def __init__(self, bq: int, bk: int, direction: str = "both"):
        self._blocks = (bq, bk)
        self._direction = direction

    def __enter__(self):
        self._prev = _FORCE.get(self._direction)
        _FORCE[self._direction] = self._blocks
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            _FORCE.pop(self._direction, None)
        else:
            _FORCE[self._direction] = self._prev
        return False


def _fence(x):
    import numpy as np
    np.asarray(x)


def _bench_inputs(sq, sk, d, dtype, biased, batch, heads):
    import jax.numpy as jnp
    import numpy as np

    jdt = jnp.bfloat16 if str(dtype) == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((batch, sq, heads, d)), jdt)
    k = jnp.asarray(rng.standard_normal((batch, sk, heads, d)), jdt)
    v = jnp.asarray(rng.standard_normal((batch, sk, heads, d)), jdt)
    bias = None
    if biased:
        bias = jnp.asarray(
            rng.standard_normal((batch, 1, 1, sk)) * 0.0, jnp.float32)
    return q, k, v, bias


def _sweep(sq, sk, make_fn, args, iters, direction="both", verbose=False,
           oracle=None, rejected=None):
    """Time make_fn() per viable (bq, bk) candidate with that candidate
    forced for ``direction``; returns {(bq, bk): seconds}.

    ``oracle(bq, bk) -> list-of-failures`` (the armed differential
    oracle, ops/pallas/verify.py) runs BEFORE a candidate is timed: a
    failing candidate is never measured — a fast wrong kernel must not
    win — and its failures land in the caller's ``rejected`` dict.
    """
    import time

    results = {}
    for bq, bk in CANDIDATES:
        if bq > sq or bk > sk or sq % bq or sk % bk:
            continue
        if oracle is not None:
            bad = oracle(bq, bk)
            if bad:
                if rejected is not None:
                    rejected[(bq, bk)] = bad
                if verbose:
                    print(f"  {direction} ({bq},{bk}): REJECTED by "
                          f"oracle — {bad[0]}")
                continue
        try:
            with force_blocks(bq, bk, direction=direction):
                f = make_fn()
                out = f(*args)                   # compile + warm
                _fence(out[0] if isinstance(out, tuple) else out)
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = f(*args)
                _fence(out[0] if isinstance(out, tuple) else out)
                dt = (time.perf_counter() - t0) / iters
            results[(bq, bk)] = dt
            if verbose:
                print(f"  {direction} ({bq},{bk}): {dt*1e3:.2f} ms")
        except Exception as e:                   # noqa: BLE001
            if verbose:
                print(f"  {direction} ({bq},{bk}): failed {e!r}")
    return results


def _candidate_oracle(d, dtype, causal, biased):
    """The armed differential oracle as a per-candidate gate, or None
    when FLAGS_pallas_verify is off (zero overhead: the sweep never
    calls into verify)."""
    from paddle_tpu.ops.pallas import verify
    if not verify.armed():
        return None

    def check(bq, bk):
        return verify.check_flash_candidate(
            bq, bk, d=d, dtype=str(dtype), causal=causal, biased=biased)

    return check


def _loss_fn(causal, bias):
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as fa

    def loss(q_, k_, v_):
        out = fa.flash_attention(q_, k_, v_, causal=causal, bias=bias)
        return out.astype(jnp.float32).sum()

    return loss


def measure(sq, sk, d, dtype="bfloat16", causal=False, biased=False,
            batch=1, heads=8, iters=3, persist=True, verbose=False,
            rejected=None):
    """Time fwd+bwd per candidate on the current device; record winner.
    With FLAGS_pallas_verify armed, candidates failing the differential
    oracle are rejected (collected in ``rejected``) instead of timed,
    and the recorded winner is stamped ``verified: true``."""
    import jax

    q, k, v, bias = _bench_inputs(sq, sk, d, dtype, biased, batch, heads)
    loss = _loss_fn(causal, bias)
    oracle = _candidate_oracle(d, dtype, causal, biased)
    results = _sweep(sq, sk,
                     lambda: jax.jit(jax.value_and_grad(
                         loss, argnums=(0, 1, 2))),
                     (q, k, v), iters, verbose=verbose, oracle=oracle,
                     rejected=rejected)
    if not results:
        return None
    best = min(results, key=results.get)
    record(sq, sk, d, dtype, causal, biased, best, persist=persist,
           verified=oracle is not None)
    return best, results


def measure_split(sq, sk, d, dtype="bfloat16", causal=False, biased=False,
                  batch=1, heads=8, iters=3, persist=True, verbose=False,
                  rejected=None):
    """Tune fwd and bwd block sizes independently.

    Pass 1 times the forward alone per candidate and records the "fwd"
    winner; pass 2, with the forward pinned to that winner, times
    fwd+bwd per candidate and records the "bwd" winner (bwd-only time
    isn't separable under jit, but with fwd pinned the candidate axis
    only moves the backward kernels).
    """
    import jax

    q, k, v, bias = _bench_inputs(sq, sk, d, dtype, biased, batch, heads)
    loss = _loss_fn(causal, bias)
    oracle = _candidate_oracle(d, dtype, causal, biased)

    fwd_res = _sweep(sq, sk, lambda: jax.jit(loss), (q, k, v), iters,
                     direction="fwd", verbose=verbose, oracle=oracle,
                     rejected=rejected)
    if not fwd_res:
        return None
    fwd_best = min(fwd_res, key=fwd_res.get)
    record(sq, sk, d, dtype, causal, biased, fwd_best, persist=persist,
           direction="fwd", verified=oracle is not None)

    with force_blocks(*fwd_best, direction="fwd"):
        bwd_res = _sweep(sq, sk,
                         lambda: jax.jit(jax.value_and_grad(
                             loss, argnums=(0, 1, 2))),
                         (q, k, v), iters, direction="bwd",
                         verbose=verbose, oracle=oracle,
                         rejected=rejected)
    if not bwd_res:
        return (fwd_best, fwd_res), None
    bwd_best = min(bwd_res, key=bwd_res.get)
    record(sq, sk, d, dtype, causal, biased, bwd_best, persist=persist,
           direction="bwd", verified=oracle is not None)
    return (fwd_best, fwd_res), (bwd_best, bwd_res)
