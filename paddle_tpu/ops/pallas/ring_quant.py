"""Per-row wire quantizer as a Pallas kernel — the codec leg of the
fused ring collectives (``parallel/ring.py``).

The fused ring spends its per-hop compute on ``wire.py``'s blocked row
codec: per-row abs-max scale, scaled round-half-to-even, clip to the
wire's quantized range.  XLA fuses that expression tree well enough on
CPU, but on TPU the ring wants the encode of chunk ``t`` to run while
chunk ``t-1`` rides the ``ppermute`` — a single fused kernel keeps the
whole encode (reduce + divide + round + clip + cast) in VMEM with one
read of the chunk, the shape the overlap schedule needs.

Layout follows the pallas guide's quantization pattern and the
``fused_adam.py`` conventions: ``(rows, D)`` blocks tiled over rows
with ``D`` a multiple of the 128-lane width, scalars as an ``(8, 1)``
block, scales emitted as a lane-broadcast ``(rows, 128)`` block (column
0 is the value — a ``(rows, 1)`` output would violate the minimum f32
tile).  int8 emits the quantized bytes directly; int4 emits int8
values in ``[-7, 7]`` and the nibble pack stays a jnp epilogue (bit
packing changes the trailing width, which Pallas blocks cannot).

Semantics are pinned to ``wire.quantize_rows_traced``: the kernel is
bitwise-identical to the traced twin in interpret mode (the
differential oracle in the tests), so swapping it in changes nothing
but the schedule.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.wire import (COLLECTIVE_WIRE_DTYPES,
                                         _pack_nibbles, normalize_wire,
                                         quantize_rows_traced)
from paddle_tpu.ops.pallas.common import no_x64

BLOCK_ROWS = 256
_LANES = 128

# tests flip this to run in interpreter mode on CPU
_INTERPRET = False


def _backend_is_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def supported() -> bool:
    return _backend_is_tpu() or _INTERPRET


def _rowquant_kernel(x_ref, s_ref, q_ref, sc_ref):
    qmax = s_ref[0, 0]
    x = x_ref[...]
    scale = jnp.max(jnp.abs(x), axis=1, keepdims=True) / qmax
    scale = jnp.where(scale > 0.0, scale, 1.0)
    q_ref[...] = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(
        jnp.int8)
    sc_ref[...] = jnp.broadcast_to(scale, (x.shape[0], _LANES))


def _kernel_quant(rows, qmax: float):
    """One fused pass over ``(R, D)`` f32 rows → (q int8, scale f32)
    with ``R`` padded to the row-block multiple (pad rows are zero →
    scale 1, q 0 — sliced back off before returning)."""
    from jax.experimental import pallas as pl

    r, d = rows.shape
    block = BLOCK_ROWS
    rows_p = -(-r // block) * block
    x = rows.astype(jnp.float32)
    if rows_p != r:
        x = jnp.pad(x, ((0, rows_p - r), (0, 0)))
    scalars = jnp.full((8, 1), jnp.float32(qmax))
    with no_x64():
        q, sc = pl.pallas_call(
            _rowquant_kernel,
            grid=(rows_p // block,),
            in_specs=[pl.BlockSpec((block, d), lambda i: (i, 0)),
                      pl.BlockSpec((8, 1), lambda i: (0, 0))],
            out_specs=[pl.BlockSpec((block, d), lambda i: (i, 0)),
                       pl.BlockSpec((block, _LANES), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows_p, d), jnp.int8),
                       jax.ShapeDtypeStruct((rows_p, _LANES),
                                            jnp.float32)],
            interpret=_INTERPRET,
        )(x, scalars)
    return q[:r], sc[:r, 0]


def ring_quant_rows(rows, wire: str, force: bool = False):
    """Kernel-accelerated twin of ``wire.quantize_rows_traced`` on
    ``(R, D)`` rows.  Falls back to the traced jnp codec off-TPU, for
    the cast wires (no per-row scale to fuse) and for widths off the
    128-lane grid; ``force=True`` takes the kernel path regardless
    (the abstract-trace hook the analysis zoo uses)."""
    wire = normalize_wire(wire, known=COLLECTIVE_WIRE_DTYPES)
    if wire not in ("int8", "int4") or rows.ndim != 2 \
            or rows.shape[-1] % _LANES or not (supported() or force):
        return quantize_rows_traced(rows, wire)
    q, scale = _kernel_quant(rows, 7.0 if wire == "int4" else 127.0)
    if wire == "int4":
        return (_pack_nibbles(q, jnp), scale)
    return (q, scale)


def xla_reference(rows, wire: str):
    """Unfused reference — the traced wire codec itself."""
    return quantize_rows_traced(rows, wire)
