"""Pallas TPU kernels — replacements for operators/fused/ CUDA kernels."""
