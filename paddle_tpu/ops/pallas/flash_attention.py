"""Flash attention — Pallas TPU kernel.

Replaces (and exceeds) the reference's fused attention inference kernels
(paddle/fluid/operators/fused/multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm) with a training-capable blockwise
online-softmax attention: the S×S score matrix never leaves VMEM, so HBM
traffic is O(S·D) instead of O(S²).

Forward = Pallas kernel over grid (batch*heads, q_blocks); the kv loop is a
fori_loop inside the kernel with running (max, sum-exp, acc) state.
Backward (round 1) = XLA recompute via jax.custom_vjp — numerically exact,
keeps the forward's memory win at inference and trades backward memory for
simplicity; a full Pallas backward kernel is the planned upgrade.

Layout: (B, S, H, D) [paddle MultiHeadAttention layout].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

BLOCK_Q = 512
BLOCK_K = 512
_MIN_BLOCK = 128


def _backend_is_tpu() -> bool:
    try:
        import jax.extend.backend as _b
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return jax.default_backend() in ("tpu", "axon")


def supported(q_shape, k_shape, no_mask: bool) -> bool:
    if not no_mask:
        return False
    if not _backend_is_tpu():
        return False
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    if d % 128 != 0 and d not in (64,):
        # lane dim must tile; 64 is fine via packing but keep it simple
        if d % 128 != 0:
            return False
    return sq % _MIN_BLOCK == 0 and sk % _MIN_BLOCK == 0 and sq >= _MIN_BLOCK \
        and sk >= _MIN_BLOCK


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k,
                seq_k, block_q):
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    qi = pl.program_id(1)

    m0 = jnp.full((q.shape[0],), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((q.shape[0],), jnp.float32)
    acc0 = jnp.zeros((q.shape[0], v_ref.shape[-1]), jnp.float32)

    n_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k = k_ref[0, pl.dslice(kb * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, pl.dslice(kb * block_k, block_k)].astype(jnp.float32)
        s = q @ k.T                                   # (bq, bk)
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_idx >= k_idx, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(jnp.isfinite(m_new)[:, None], p, 0.0)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(jnp.isfinite(m), alpha, 0.0)
        l_new = alpha * l + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + p @ v
        return m_new, l_new, acc_new

    def run_all():
        if causal:
            # only kv blocks at or before this q block contribute
            last = (qi + 1) * block_q
            n_needed = pl.cdiv(last, block_k)
            return jax.lax.fori_loop(0, n_needed, body, (m0, l0, acc0))
        return jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))

    m, l, acc = run_all()
    out = acc / jnp.maximum(l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(BLOCK_Q, sq)
    block_k = min(BLOCK_K, sk)

    # fold batch and heads; put seq last-but-one for tiling
    qt = jnp.einsum("bshd->bhsd", q).reshape(b * h, sq, d)
    kt = jnp.einsum("bshd->bhsd", k).reshape(b * h, sk, d)
    vt = jnp.einsum("bshd->bhsd", v).reshape(b * h, sk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_k=sk, block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
    )(qt, kt, vt)
    return jnp.einsum("bhsd->bshd", out.reshape(b, h, sq, d))


def _xla_reference(q, k, v, scale, causal):
    qh = jnp.einsum("bshd->bhsd", q)
    kh = jnp.einsum("bshd->bhsd", k)
    vh = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if causal:
        sq_, sk_ = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq_, sk_), dtype=bool), k=sk_ - sq_)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    return jnp.einsum("bhsd->bshd", o)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, scale, causal)


def _fa_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # XLA recompute backward (exact): jax.vjp of the reference formula
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_reference(q_, k_, v_, scale,
                                                       causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
