"""Flash attention — Pallas TPU kernels, forward AND backward.

Replaces (and exceeds) the reference's fused attention inference kernels
(paddle/fluid/operators/fused/multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm) with a training-capable blockwise
online-softmax attention: the S×S score matrix never leaves VMEM, so HBM
traffic is O(S·D) instead of O(S²) in BOTH directions.

Forward: grid (batch*heads, q_blocks, kv_blocks); the kv axis is the
innermost, sequentially-executed grid axis, so running (max, sum-exp, acc)
state lives in VMEM scratch.  The per-row logsumexp is written out as a
residual for the backward.

Backward: two kernels, both recomputing p-tiles from (q, k, lse):
  - dq:     grid (bh, q_blocks, kv_blocks), dq accumulates in VMEM over kv.
  - dk/dv:  grid (bh, kv_blocks, q_blocks), dk/dv accumulate over q.
The softmax-jacobian row term delta = rowsum(dO * O) is an O(S·D) XLA
precompute.  This is the standard FlashAttention-2 backward dataflow.

Causal masking is END-ALIGNED (query i sees keys j with j <= i + sk - sq),
matching the XLA fallback's ``tril(k=sk-sq)`` convention; ``supported()``
rejects causal sq > sk, where end-alignment would leave fully-masked rows.

Layout: (B, S, H, D) [paddle MultiHeadAttention layout].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# Block choice: isolated fp32 fwd+bwd sweeps at S=8192 prefer 256/256,
# but in-model (bf16 + remat + optimizer, GPT-2 and 8k-GPT train steps)
# 512/512 measures ~20% faster end-to-end — bf16 tiles halve VMEM
# pressure, so the larger block wins where it matters.  _pick_block
# halves toward _MIN_BLOCK for sequences 512 doesn't divide.
BLOCK_Q = 512
BLOCK_K = 512
_MIN_BLOCK = 128

# tests flip this to run the kernels in interpreter mode on CPU
_INTERPRET = False


def _backend_is_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def supported(q_shape, k_shape, no_mask: bool, causal: bool = False) -> bool:
    if not no_mask:
        return False
    if not (_backend_is_tpu() or _INTERPRET):
        return False
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    if causal and sq > sk:
        # end-aligned causal with more queries than keys leaves rows with
        # no visible key; semantics degenerate — use the XLA path
        return False
    if d % 128 != 0 and d not in (64,):
        return False
    # the grid floors seq/block: a remainder would leave trailing queries
    # unwritten and trailing keys ignored, so block divisibility is required
    block_q = _pick_block(BLOCK_Q, sq)
    block_k = _pick_block(BLOCK_K, sk)
    if sq % block_q or sk % block_k:
        return False
    return sq % _MIN_BLOCK == 0 and sk % _MIN_BLOCK == 0 and sq >= _MIN_BLOCK \
        and sk >= _MIN_BLOCK



def _pick_block(pref: int, seq: int) -> int:
    """Largest block <= pref that divides seq, halving down to _MIN_BLOCK
    (keeps e.g. seq=384 on the kernel path instead of silently falling
    back to the O(S^2) XLA reference)."""
    b = min(pref, seq)
    while b > _MIN_BLOCK and seq % b:
        b //= 2
    return max(b, _MIN_BLOCK)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale, causal, block_k, block_q, n_kb, off):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal (end-aligned): kv blocks strictly beyond the shifted diagonal
    # contribute nothing
    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < \
            (qi + 1) * jnp.int32(block_q) + jnp.int32(off)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale       # (bq, d)
        k = k_ref[0].astype(jnp.float32)               # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = q @ k.T                                    # (bq, bk)
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_idx + off >= k_idx, s, -jnp.inf)
        m_prev = m_scr[...]                            # (bq, 1)
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(jnp.isfinite(m_prev), alpha, 0.0)
        m_scr[...] = m_new
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + p @ v

    @pl.when(kb == n_kb - 1)
    def _finish():
        l = l_scr[...]
        out = acc_scr[...] / jnp.maximum(l, 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)
        # logsumexp residual; rows with zero mass get -inf (p rebuild → 0)
        lse_ref[0] = m_scr[...] + jnp.log(jnp.maximum(l, 1e-30))


def _flash_fwd(q, k, v, scale, causal):
    """Returns (out (B,S,H,D), lse (B*H, Sq, 1) float32)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(BLOCK_Q, sq)
    block_k = _pick_block(BLOCK_K, sk)
    n_kb = sk // block_k

    # fold batch and heads; put seq last-but-one for tiling
    qt = jnp.einsum("bshd->bhsd", q).reshape(b * h, sq, d)
    kt = jnp.einsum("bshd->bhsd", k).reshape(b * h, sk, d)
    vt = jnp.einsum("bshd->bhsd", v).reshape(b * h, sk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, block_q=block_q, n_kb=n_kb,
                               off=sk - sq)
    # Mosaic rejects 64-bit types; the framework enables x64 globally, so
    # pin 32-bit mode for the kernel trace (index maps would emit i64)
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
            kernel,
            grid=(b * h, sq // block_q, n_kb),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda bh, qi, kb: (bh, qi, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (bh, kb, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (bh, kb, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda bh, qi, kb: (bh, qi, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda bh, qi, kb: (bh, qi, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            interpret=_INTERPRET,
        )(qt, kt, vt)
    return jnp.einsum("bhsd->bshd", out.reshape(b, h, sq, d)), lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k, off):
    """Recompute the (bq, bk) probability tile from saved lse."""
    s = (q @ k.T) * scale
    if causal:
        q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_idx + off >= k_idx, s, -jnp.inf)
    p = jnp.exp(s - lse)
    return jnp.where(jnp.isfinite(s) & jnp.isfinite(lse), p, 0.0)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_scr, *, scale, causal, block_q, block_k, n_kb, off):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < \
            (qi + 1) * jnp.int32(block_q) + jnp.int32(off)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                               # (bq, 1)
        delta = delta_ref[0]
        p = _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k,
                       off)
        dp = do @ v.T                                  # (bq, bk)
        ds = p * (dp - delta)
        acc_scr[...] += (ds @ k) * scale

    @pl.when(kb == n_kb - 1)
    def _finish():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_k, n_qb, off):
    from jax.experimental import pallas as pl

    kb = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < \
            (qi + 1) * jnp.int32(block_q) + jnp.int32(off)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        p = _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k,
                       off)
        dv_scr[...] += p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta)
        dk_scr[...] += (ds.T @ q) * scale

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(BLOCK_Q, sq)
    block_k = _pick_block(BLOCK_K, sk)
    n_qb = sq // block_q
    n_kb = sk // block_k
    off = sk - sq

    qt = jnp.einsum("bshd->bhsd", q).reshape(b * h, sq, d)
    kt = jnp.einsum("bshd->bhsd", k).reshape(b * h, sk, d)
    vt = jnp.einsum("bshd->bhsd", v).reshape(b * h, sk, d)
    dot = jnp.einsum("bshd->bhsd", do).reshape(b * h, sq, d)
    # delta_i = sum_d dO_i · O_i  (softmax-jacobian row term), O(S·D)
    delta = jnp.einsum("bshd,bshd->bsh", do.astype(jnp.float32),
                       o.astype(jnp.float32))
    delta = jnp.einsum("bsh->bhs", delta).reshape(b * h, sq, 1)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, qi, kb: (bh, kb, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda bh, qi, kb: (bh, qi, 0))
    # dkv grid order is (bh, kb, qi)
    q_spec_t = pl.BlockSpec((1, block_q, d), lambda bh, kb, qi: (bh, qi, 0))
    k_spec_t = pl.BlockSpec((1, block_k, d), lambda bh, kb, qi: (bh, kb, 0))
    row_spec_t = pl.BlockSpec((1, block_q, 1),
                              lambda bh, kb, qi: (bh, qi, 0))

    with jax.enable_x64(False):
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, n_kb=n_kb,
                              off=off),
            grid=(b * h, n_qb, n_kb),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=_INTERPRET,
        )(qt, kt, vt, dot, lse, delta)

        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, n_qb=n_qb,
                              off=off),
            grid=(b * h, n_kb, n_qb),
            in_specs=[q_spec_t, k_spec_t, k_spec_t, q_spec_t, row_spec_t,
                      row_spec_t],
            out_specs=[k_spec_t, k_spec_t],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
                jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=_INTERPRET,
        )(qt, kt, vt, dot, lse, delta)

    unfold = lambda x, s: jnp.einsum(
        "bhsd->bshd", x.reshape(b, h, s, d))
    return unfold(dq, sq), unfold(dk, sk), unfold(dv, sk)


def _xla_reference(q, k, v, scale, causal):
    qh = jnp.einsum("bshd->bhsd", q)
    kh = jnp.einsum("bshd->bhsd", k)
    vh = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if causal:
        sq_, sk_ = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq_, sk_), dtype=bool), k=sk_ - sq_)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    return jnp.einsum("bhsd->bshd", o)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, _ = _flash_fwd(q, k, v, scale, causal)
    return out


def _fa_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, res, g):
    q, k, v, o, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_bwd(q, k, v, o, lse, g, scale, causal)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
