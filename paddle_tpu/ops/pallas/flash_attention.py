"""Flash attention — Pallas TPU kernel.

Replaces (and exceeds) the reference's fused attention inference kernels
(paddle/fluid/operators/fused/multihead_matmul_op.cu,
fused_embedding_eltwise_layernorm) with a training-capable blockwise
online-softmax attention: the S×S score matrix never leaves VMEM, so HBM
traffic is O(S·D) instead of O(S²).

Forward = Pallas kernel over grid (batch*heads, q_blocks); the kv loop is a
fori_loop inside the kernel with running (max, sum-exp, acc) state.
Backward (round 1) = XLA recompute via jax.custom_vjp — numerically exact,
keeps the forward's memory win at inference and trades backward memory for
simplicity; a full Pallas backward kernel is the planned upgrade.

Layout: (B, S, H, D) [paddle MultiHeadAttention layout].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

BLOCK_Q = 512
BLOCK_K = 512
_MIN_BLOCK = 128


def _backend_is_tpu() -> bool:
    try:
        import jax.extend.backend as _b
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return jax.default_backend() in ("tpu", "axon")


def supported(q_shape, k_shape, no_mask: bool) -> bool:
    if not no_mask:
        return False
    if not _backend_is_tpu():
        return False
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    if d % 128 != 0 and d not in (64,):
        # lane dim must tile; 64 is fine via packing but keep it simple
        if d % 128 != 0:
            return False
    # the grid floors seq/block: a remainder would leave trailing queries
    # unwritten and trailing keys ignored, so block divisibility is required
    block_q = min(BLOCK_Q, sq)
    block_k = min(BLOCK_K, sk)
    if sq % block_q or sk % block_k:
        return False
    return sq % _MIN_BLOCK == 0 and sk % _MIN_BLOCK == 0 and sq >= _MIN_BLOCK \
        and sk >= _MIN_BLOCK


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                scale, causal, block_k, block_q, n_kb):
    """Grid (bh, q_blocks, kv_blocks): the kv dimension is the innermost,
    sequentially-executed grid axis, so (m, l, acc) survive in VMEM scratch
    across kv steps — only one (block_q × block_k) tile is live at a time
    and HBM traffic stays O(S·D) at any sequence length."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: kv blocks strictly above the diagonal contribute nothing
    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < (qi + 1) * jnp.int32(block_q)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale       # (bq, d)
        k = k_ref[0].astype(jnp.float32)               # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = q @ k.T                                    # (bq, bk)
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_idx >= k_idx, s, -jnp.inf)
        m_prev = m_scr[...]                            # (bq, 1)
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(jnp.isfinite(m_prev), alpha, 0.0)
        m_scr[...] = m_new
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + p @ v

    @pl.when(kb == n_kb - 1)
    def _finish():
        out = acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(BLOCK_Q, sq)
    block_k = min(BLOCK_K, sk)
    n_kb = sk // block_k

    # fold batch and heads; put seq last-but-one for tiling
    qt = jnp.einsum("bshd->bhsd", q).reshape(b * h, sq, d)
    kt = jnp.einsum("bshd->bhsd", k).reshape(b * h, sk, d)
    vt = jnp.einsum("bshd->bhsd", v).reshape(b * h, sk, d)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, block_q=block_q, n_kb=n_kb)
    # Mosaic rejects 64-bit types; the framework enables x64 globally, so
    # pin 32-bit mode for the kernel trace (index maps would emit i64)
    with jax.enable_x64(False):
        out = pl.pallas_call(
            kernel,
            grid=(b * h, sq // block_q, n_kb),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda bh, qi, kb: (bh, qi, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (bh, kb, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (bh, kb, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda bh, qi, kb: (bh, qi, 0)),
            out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
        )(qt, kt, vt)
    return jnp.einsum("bhsd->bshd", out.reshape(b, h, sq, d))


def _xla_reference(q, k, v, scale, causal):
    qh = jnp.einsum("bshd->bhsd", q)
    kh = jnp.einsum("bshd->bhsd", k)
    vh = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if causal:
        sq_, sk_ = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq_, sk_), dtype=bool), k=sk_ - sq_)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    return jnp.einsum("bhsd->bshd", o)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, scale, causal)


def _fa_fwd(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out = _flash_fwd(q, k, v, scale, causal)
    return out, (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # XLA recompute backward (exact): jax.vjp of the reference formula
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_reference(q_, k_, v_, scale,
                                                       causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
