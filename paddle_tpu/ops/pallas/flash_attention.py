"""Flash attention — Pallas TPU kernels, forward AND backward, with masks.

Replaces (and exceeds) the reference's fused attention kernels
(paddle/fluid/operators/fused/multihead_matmul_op.cu — which takes a
bias_qk mask input, and fused_embedding_eltwise_layernorm) with a
training-capable blockwise online-softmax attention: the S×S score matrix
never leaves VMEM, so HBM traffic is O(S·D) instead of O(S²) in BOTH
directions.

Masking (all composable with causal):
  - ``bias``: additive float mask, broadcastable (B|1, H|1, Sq|1, Sk).
    Loaded tile-wise; for the common padding shape (B, 1, 1, Sk) the
    extra HBM traffic is O(B·Sk) — negligible.  Bool masks are converted
    by the dispatcher to 0/-inf additive form.  d(bias) is computed by a
    dedicated reduction kernel (dead-code-eliminated under jit when the
    mask does not require grad — the usual case).
  - ``q_segment_ids``/``kv_segment_ids``: (B, Sq)/(B, Sk) int ids for
    packed sequences; q attends to k iff ids match.  O(B·S) memory where
    a materialised packed mask would be O(B·S²).

Forward: grid (batch*heads, q_blocks, kv_blocks); the kv axis is the
innermost, sequentially-executed grid axis, so running (max, sum-exp, acc)
state lives in VMEM scratch.  The per-row logsumexp is written out as a
residual for the backward.

Backward: three kernels, all recomputing p-tiles from (q, k, lse, mask):
  - dq:     grid (bh, q_blocks, kv_blocks), dq accumulates in VMEM over kv.
  - dk/dv:  grid (bh, kv_blocks, q_blocks), dk/dv accumulate over q.
  - dbias:  grid (g, kv_blocks, q_blocks, r) where g indexes the bias'
    own batch*head extent and r sweeps the broadcast (reduced) b/h
    extent; ds tiles accumulate in VMEM over the innermost reduction
    axes.  Only traced when a bias is present; DCE'd when unused.
The softmax-jacobian row term delta = rowsum(dO * O) is an O(S·D) XLA
precompute.  This is the standard FlashAttention-2 backward dataflow.

Rows with no visible key (fully masked) produce output 0 with zero
gradients (lse = -inf); the XLA fallback's uniform-attention behaviour on
such rows is an artifact of its -1e30 clamp, not a semantic to preserve.

Causal masking is END-ALIGNED (query i sees keys j with j <= i + sk - sq),
matching the XLA fallback's ``tril(k=sk-sq)`` convention; ``supported()``
rejects causal sq > sk, where end-alignment would leave fully-masked rows.

Layout: (B, S, H, D) [paddle MultiHeadAttention layout].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

# Block choice: isolated fp32 fwd+bwd sweeps at S=8192 prefer 256/256,
# but in-model (bf16 + remat + optimizer, GPT-2 and 8k-GPT train steps)
# 512/512 measures ~20% faster end-to-end — bf16 tiles halve VMEM
# pressure, so the larger block wins where it matters.  _pick_block
# halves toward _MIN_BLOCK for sequences 512 doesn't divide.
BLOCK_Q = 512
BLOCK_K = 512
_MIN_BLOCK = 128

# tests flip this to run the kernels in interpreter mode on CPU
_INTERPRET = False

_NEG_INF = float("-inf")

from paddle_tpu.ops.pallas.common import dot_nt as _dot_nt, no_x64  # noqa: E402


def _backend_is_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def _canon_bias_shape(bias_shape, b, h, sq, sk):
    """Canonicalise a broadcastable mask/bias shape to (Bb, Hb, Sqb, Sk).

    Returns the 4-tuple, or None if the shape can't ride the kernel
    (each dim must be 1 or full; the key dim must be full).
    """
    s = tuple(int(d) for d in bias_shape)
    if len(s) > 4 or len(s) < 1:
        return None
    s = (1,) * (4 - len(s)) + s
    bb, hb, sqb, skb = s
    if skb != sk:
        return None
    if bb not in (1, b) or hb not in (1, h) or sqb not in (1, sq):
        return None
    return (bb, hb, sqb, skb)


def supported(q_shape, k_shape, no_mask: bool = True, causal: bool = False,
              bias_shape=None, segments: bool = False) -> bool:
    """Can the Pallas kernel serve this attention call?

    ``no_mask`` is the legacy round-2 argument: a mask used to force the
    XLA fallback.  Now a mask is fine as long as it is expressible as a
    canonical additive bias (``bias_shape``) and/or segment ids.
    """
    if not no_mask and bias_shape is None and not segments:
        return False
    if not (_backend_is_tpu() or _INTERPRET):
        return False
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    if causal and sq > sk:
        # end-aligned causal with more queries than keys leaves rows with
        # no visible key; semantics degenerate — use the XLA path
        return False
    if not _INTERPRET and not causal and sq < 1024 and sk < 1024:
        # empirical dispatch crossover (BERT-base class, bf16, one chip):
        # XLA's fused attention wins short non-causal sequences (S=128:
        # 146k vs 97k tok/s in-model; S=512: 104k vs 97k), the kernel wins
        # from S≈2048 (58.8k vs 53.4k) and dominates at 8k+ where the XLA
        # path hits its O(S²) HBM cliff.  Causal configs always take the
        # kernel — block skipping halves the work (S=1024 in-model win).
        return False
    if d % 128 != 0 and d not in (64,):
        return False
    if bias_shape is not None and \
            _canon_bias_shape(bias_shape, b, h, sq, sk) is None:
        return False
    if bias_shape is not None or segments:
        # the bias/segment tile specs are not tail-masked, so the mask
        # path keeps the block-divisibility requirement
        block_q = _pick_block(BLOCK_Q, sq)
        block_k = _pick_block(BLOCK_K, sk)
        if sq % block_q or sk % block_k:
            return False
        return sq % _MIN_BLOCK == 0 and sk % _MIN_BLOCK == 0 \
            and sq >= _MIN_BLOCK and sk >= _MIN_BLOCK
    # no mask: non-divisible sequences ride cdiv grids with tail-masked
    # blocks (out-of-range keys scored -inf, tail q/do rows zeroed in the
    # backward contractions); sub-block sequences still fall back to XLA
    return sq >= _MIN_BLOCK and sk >= _MIN_BLOCK



def _pick_block(pref: int, seq: int) -> int:
    """Largest block <= pref that divides seq, halving down to _MIN_BLOCK
    (keeps e.g. seq=384 on the kernel path instead of silently falling
    back to the O(S^2) XLA reference)."""
    b = min(pref, seq)
    while b > _MIN_BLOCK and seq % b:
        b //= 2
    return max(b, _MIN_BLOCK)


def _blocks_for(sq, sk, d, dtype, causal, biased, direction="fwd"):
    """(block_q, block_k) — the measured autotune cache first (keyed on
    shape/dtype/mask class and, for the backward, the direction: the
    dq/dkv kernels have different per-tile reuse than the forward so
    their winning tile can differ), else the BLOCK_Q/K heuristic; either
    way halved until it divides the sequence."""
    from paddle_tpu.ops.pallas import autotune
    hit = autotune.lookup(sq, sk, d, str(dtype), causal, biased,
                          direction=direction)
    bq, bk = hit if hit else (BLOCK_Q, BLOCK_K)
    return _pick_block(bq, sq), _pick_block(bk, sk)


def _bias_g_map(bb, hb, h):
    """bh (= b*h + head) → block index into the folded (Bb*Hb, ...) bias."""
    if bb == 1 and hb == 1:
        return lambda bh: 0
    if bb == 1:
        return lambda bh: bh % h       # bias indexed by head only
    if hb == 1:
        return lambda bh: bh // h      # bias indexed by batch only
    return lambda bh: bh


def _mask_tile(s, bias_ref, qs_ref, ks_ref):
    """Apply bias/segment tiles to a (bq, bk) score tile."""
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    if qs_ref is not None:
        s = jnp.where(qs_ref[0] == ks_ref[0], s, _NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(*args, scale, causal, block_k, block_q, n_kb, off,
                has_bias, has_segs, sk, tail_k):
    from jax.experimental import pallas as pl

    n_in = 3 + (1 if has_bias else 0) + (2 if has_segs else 0)
    q_ref, k_ref, v_ref = args[:3]
    i = 3
    bias_ref = None
    qs_ref = ks_ref = None
    if has_bias:
        bias_ref = args[i]
        i += 1
    if has_segs:
        qs_ref, ks_ref = args[i], args[i + 1]
        i += 2
    o_ref, lse_ref = args[n_in], args[n_in + 1]
    m_scr, l_scr, acc_scr = args[n_in + 2:]

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal (end-aligned): kv blocks strictly beyond the shifted diagonal
    # contribute nothing
    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < \
            (qi + 1) * jnp.int32(block_q) + jnp.int32(off)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]                                   # (bq, d) input dtype
        k = k_ref[0]                                   # (bk, d)
        v = v_ref[0]
        # MXU at input rate (bf16 on chip), f32 accumulation; scale applied
        # to the f32 product
        s = _dot_nt(q, k) * scale                      # (bq, bk) f32
        s = _mask_tile(s, bias_ref, qs_ref, ks_ref)
        if causal or tail_k:
            k_idx = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
        if tail_k:
            # the last kv block overruns sk: out-of-range key columns
            # score -inf (exp to 0) and their value rows are zeroed so
            # padding garbage never reaches the p·v accumulate
            s = jnp.where(k_idx < sk, s, -jnp.inf)
            v_row = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0)
            v = jnp.where(v_row < sk, v, 0)
        if causal:
            q_idx = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            s = jnp.where(q_idx + off >= k_idx, s, -jnp.inf)
        m_prev = m_scr[...]                            # (bq, 1)
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(jnp.isfinite(m_new), p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(jnp.isfinite(m_prev), alpha, 0.0)
        m_scr[...] = m_new
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(kb == n_kb - 1)
    def _finish():
        l = l_scr[...]
        out = acc_scr[...] / jnp.maximum(l, 1e-30)
        o_ref[0] = out.astype(o_ref.dtype)
        # logsumexp residual; rows with zero mass get -inf (p rebuild → 0)
        lse_ref[0] = jnp.where(
            l > 0.0, m_scr[...] + jnp.log(jnp.maximum(l, 1e-30)), -jnp.inf)


def _mask_specs(pl, b, h, sqb, g_map, block_q, block_k, has_bias, has_segs,
                order):
    """Block specs for (bias?, qseg?, kseg?) under grid order
    'qk' = (bh, qi, kb) or 'kq' = (bh, kb, qi)."""
    specs = []
    if order == "qk":
        pick = lambda f: (lambda bh, qi, kb: f(bh, qi, kb))
    else:
        pick = lambda f: (lambda bh, kb, qi: f(bh, qi, kb))
    if has_bias:
        bq_b = block_q if sqb > 1 else 1
        specs.append(pl.BlockSpec(
            (1, bq_b, block_k),
            pick(lambda bh, qi, kb: (g_map(bh), qi if sqb > 1 else 0, kb))))
    if has_segs:
        specs.append(pl.BlockSpec(
            (1, block_q, 1), pick(lambda bh, qi, kb: (bh // h, qi, 0))))
        specs.append(pl.BlockSpec(
            (1, 1, block_k), pick(lambda bh, qi, kb: (bh // h, 0, kb))))
    return specs


def _mask_inputs(bias, qseg, kseg):
    ins = []
    if bias is not None:
        bb, hb, sqb, sk = bias.shape
        ins.append(bias.reshape(bb * hb, sqb, sk))
    if qseg is not None:
        ins.append(qseg[:, :, None])
        ins.append(kseg[:, None, :])
    return ins


def _fold(x, b, h):
    """(B, S, H, D) → (B*H, S, D) — the kernels' tiling layout."""
    s, d = x.shape[1], x.shape[3]
    return jnp.einsum("bshd->bhsd", x).reshape(b * h, s, d)


def _unfold(x, b, h):
    """(B*H, S, D) → (B, S, H, D)."""
    s, d = x.shape[1], x.shape[2]
    return jnp.einsum("bhsd->bshd", x.reshape(b, h, s, d))


def _flash_fwd(q, k, v, bias, qseg, kseg, scale, causal):
    """Returns (out (B,S,H,D), lse (B*H, Sq, 1) float32)."""
    b, sq, h, d = q.shape
    out_f, lse = _flash_fwd_folded(_fold(q, b, h), _fold(k, b, h),
                                   _fold(v, b, h), bias, qseg, kseg,
                                   scale, causal, h)
    return _unfold(out_f, b, h), lse


def _flash_fwd_folded(qt, kt, vt, bias, qseg, kseg, scale, causal, h):
    """Core forward on pre-folded (B*H, S, D) operands.

    Returns (out (B*H, Sq, D), lse (B*H, Sq, 1) f32).  Folding is split
    out so the custom-vjp can keep the folded operands as residuals: the
    backward kernels want exactly this layout, and re-deriving it from
    (B,S,H,D) residuals cost a measured ~5 ms/step of pure HBM copies on
    the GPT-2 345M profile (perf/gpt2_mfu_analysis.md, 'copy' row).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = qt.shape
    b = bh // h
    sk = kt.shape[1]
    has_bias = bias is not None
    has_segs = qseg is not None
    block_q, block_k = _blocks_for(sq, sk, d, qt.dtype, causal,
                                   has_bias or has_segs)
    n_qb = -(-sq // block_q)
    n_kb = -(-sk // block_k)
    if has_bias:
        bb, hb, sqb, _ = bias.shape
        g_map = _bias_g_map(bb, hb, h)
    else:
        sqb, g_map = 1, None

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, block_q=block_q, n_kb=n_kb,
                               off=sk - sq, has_bias=has_bias,
                               has_segs=has_segs, sk=sk,
                               tail_k=bool(sk % block_k))
    # Mosaic rejects 64-bit types; the framework enables x64 globally, so
    # pin 32-bit mode for the kernel trace (index maps would emit i64)
    with no_x64():
        out, lse = pl.pallas_call(
            kernel,
            grid=(bh, n_qb, n_kb),
            in_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda bh, qi, kb: (bh, qi, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (bh, kb, 0)),
                pl.BlockSpec((1, block_k, d),
                             lambda bh, qi, kb: (bh, kb, 0)),
            ] + _mask_specs(pl, b, h, sqb, g_map, block_q, block_k,
                            has_bias, has_segs, "qk"),
            out_specs=[
                pl.BlockSpec((1, block_q, d),
                             lambda bh, qi, kb: (bh, qi, 0)),
                pl.BlockSpec((1, block_q, 1),
                             lambda bh, qi, kb: (bh, qi, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sq, d), qt.dtype),
                jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            interpret=_INTERPRET,
        )(qt, kt, vt, *_mask_inputs(bias, qseg, kseg))
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k, off,
               bias_ref=None, qs_ref=None, ks_ref=None, sk=0,
               tail_k=False):
    """Recompute the (bq, bk) probability tile from saved lse.  q/k stay in
    input dtype (bf16 on chip); the product accumulates f32.

    Non-finite-input behavior (changed from the earlier full-tile
    ``isfinite(s)`` guard): only the fully-masked-row case (lse=-inf) is
    zeroed below; a +inf/nan *score* with finite lse — corrupt q/k or a
    user bias carrying +inf/nan — now nan-propagates into p and the
    grads, where the old guard silently zeroed it.  Finite inputs are
    unaffected (masking uses -inf, which exps to 0).  The propagated nan
    is the intended signal: FLAGS_check_nan_inf (or ResilientTrainStep)
    catches it at step granularity — if you are debugging nan grads that
    trace here, inspect the inputs/bias, not this kernel."""
    s = _dot_nt(q, k) * scale
    s = _mask_tile(s, bias_ref, qs_ref, ks_ref)
    if causal or tail_k:
        k_idx = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if tail_k:
        # out-of-range key columns of the tail kv block (callers zero the
        # matching k/v rows, so these columns are 0·q dots, not garbage)
        s = jnp.where(k_idx < sk, s, -jnp.inf)
    if causal:
        q_idx = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        s = jnp.where(q_idx + off >= k_idx, s, -jnp.inf)
    p = jnp.exp(s - lse)
    # masked entries (s=-inf, lse finite) already exp to 0; the only nan
    # source is a fully-masked row (lse=-inf), so one (bq,1) row guard
    # replaces two full-tile isfinite sweeps
    return jnp.where(jnp.isfinite(lse), p, 0.0)


def _split_bwd_args(args, has_bias, has_segs, n_out):
    """(q, k, v, do, lse, delta, bias?, qs?, ks?) + outs + scratch."""
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = args[:6]
    i = 6
    bias_ref = qs_ref = ks_ref = None
    if has_bias:
        bias_ref = args[i]
        i += 1
    if has_segs:
        qs_ref, ks_ref = args[i], args[i + 1]
        i += 2
    outs = args[i:i + n_out]
    scratch = args[i + n_out:]
    return (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            bias_ref, qs_ref, ks_ref, outs, scratch)


def _tail_zero(x, origin, limit):
    """Zero rows of a (rows, d) tile whose global index >= limit."""
    row = origin + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(row < limit, x, 0)


def _bwd_dq_kernel(*args, scale, causal, block_q, block_k, n_kb, off,
                   has_bias, has_segs, sk, tail_k):
    from jax.experimental import pallas as pl

    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, qs_ref,
     ks_ref, (dq_ref,), (acc_scr,)) = _split_bwd_args(
        args, has_bias, has_segs, 1)

    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < \
            (qi + 1) * jnp.int32(block_q) + jnp.int32(off)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                               # (bq, 1)
        delta = delta_ref[0]
        if tail_k:
            # zero the overrun k/v rows: ds's zero tail columns must
            # contract against zeros, not padding garbage (0·garbage is
            # NaN-poisoned in interpret mode)
            k = _tail_zero(k, kb * block_k, sk)
            v = _tail_zero(v, kb * block_k, sk)
        p = _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k,
                       off, bias_ref, qs_ref, ks_ref, sk=sk, tail_k=tail_k)
        dp = _dot_nt(do, v)                            # (bq, bk) f32
        ds = p * (dp - delta)
        acc_scr[...] += jnp.dot(ds.astype(k.dtype), k,
                                preferred_element_type=jnp.float32) * scale

    @pl.when(kb == n_kb - 1)
    def _finish():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*args, scale, causal, block_q, block_k, n_qb, off,
                    has_bias, has_segs, sq, tail_q, sk, tail_k):
    from jax.experimental import pallas as pl

    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, qs_ref,
     ks_ref, (dk_ref, dv_ref), (dk_scr, dv_scr)) = _split_bwd_args(
        args, has_bias, has_segs, 2)

    kb = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    needed = True
    if causal:
        needed = kb * jnp.int32(block_k) < \
            (qi + 1) * jnp.int32(block_q) + jnp.int32(off)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        if tail_q:
            # the tail q block's overrun rows carry garbage q/do/lse/
            # delta; they are contracted INTO every dk/dv entry here, so
            # both operands of each contraction must be zeroed rows
            q = _tail_zero(q, qi * block_q, sq)
            do = _tail_zero(do, qi * block_q, sq)
        if tail_k:
            k = _tail_zero(k, kb * block_k, sk)
            v = _tail_zero(v, kb * block_k, sk)
        p = _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k,
                       off, bias_ref, qs_ref, ks_ref, sk=sk, tail_k=tail_k)
        if tail_q:
            # p rows from garbage lse are NaN — zero them explicitly
            p = _tail_zero(p, qi * block_q, sq)
        # contract the query axis: pT@do and dsT@q with bf16 operands
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = _dot_nt(do, v)
        ds = p * (dp - delta)
        if tail_q:
            # garbage delta rows poison ds even where p is 0 (0·NaN)
            ds = _tail_zero(ds, qi * block_q, sq)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dbias_kernel(*args, scale, causal, block_q, block_k, n_qb, n_r,
                      off, sq_full, has_segs):
    """ds accumulated over the bias' broadcast extents.

    Grid (g, kb, qi, r): r sweeps the reduced batch*head extent; when the
    bias has no query dim (sq_full=False) qi is reduced as well.  Both
    reduction axes are innermost, so output-block revisits are
    consecutive — accumulate in VMEM, write on the last visit.
    """
    from jax.experimental import pallas as pl

    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, bias_ref, qs_ref,
     ks_ref, (db_ref,), (db_scr,)) = _split_bwd_args(args, True, has_segs, 1)

    kb = pl.program_id(1)
    qi = pl.program_id(2)
    r = pl.program_id(3)

    first = (r == 0) if sq_full else jnp.logical_and(r == 0, qi == 0)
    last = (r == n_r - 1) if sq_full else \
        jnp.logical_and(r == n_r - 1, qi == n_qb - 1)

    @pl.when(first)
    def _init():
        db_scr[...] = jnp.zeros_like(db_scr)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    p = _rebuild_p(q, k, lse, scale, causal, qi, kb, block_q, block_k,
                   off, bias_ref, qs_ref, ks_ref)
    dp = _dot_nt(do, v)
    ds = p * (dp - delta)
    if sq_full:
        db_scr[...] += ds
    else:
        db_scr[...] += jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(last)
    def _finish():
        db_ref[0] = db_scr[...].astype(db_ref.dtype)


def _flash_bwd_folded(qt, kt, vt, bias, qseg, kseg, ot, lse, do, scale,
                      causal, h, want_dbias=True):
    """Backward on the pre-folded residuals saved by the forward.

    ``qt/kt/vt/ot`` are (B*H, S, D) — exactly the kernels' layout, so the
    only layout transpose left in the whole backward is folding the
    incoming ``do`` cotangent and unfolding the dq/dk/dv results.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = qt.shape
    b = bh // h
    sk = kt.shape[1]
    has_bias = bias is not None
    has_segs = qseg is not None
    block_q, block_k = _blocks_for(sq, sk, d, qt.dtype, causal,
                                   has_bias or has_segs, direction="bwd")
    n_qb = -(-sq // block_q)
    n_kb = -(-sk // block_k)
    tail_q = bool(sq % block_q)
    tail_k = bool(sk % block_k)
    off = sk - sq

    if has_bias:
        bb, hb, sqb, _ = bias.shape
        g_map = _bias_g_map(bb, hb, h)
    else:
        sqb, g_map = 1, None

    dot = _fold(do, b, h)
    # delta_i = sum_d dO_i · O_i  (softmax-jacobian row term), O(S·D)
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, kb: (bh, qi, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, qi, kb: (bh, kb, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda bh, qi, kb: (bh, qi, 0))
    # dkv grid order is (bh, kb, qi)
    q_spec_t = pl.BlockSpec((1, block_q, d), lambda bh, kb, qi: (bh, qi, 0))
    k_spec_t = pl.BlockSpec((1, block_k, d), lambda bh, kb, qi: (bh, kb, 0))
    row_spec_t = pl.BlockSpec((1, block_q, 1),
                              lambda bh, kb, qi: (bh, qi, 0))

    mask_ins = _mask_inputs(bias, qseg, kseg)

    with no_x64():
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, n_kb=n_kb,
                              off=off, has_bias=has_bias, has_segs=has_segs,
                              sk=sk, tail_k=tail_k),
            grid=(bh, n_qb, n_kb),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
            + _mask_specs(pl, b, h, sqb, g_map, block_q, block_k,
                          has_bias, has_segs, "qk"),
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct((bh, sq, d), qt.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=_INTERPRET,
        )(qt, kt, vt, dot, lse, delta, *mask_ins)

        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                              block_q=block_q, block_k=block_k, n_qb=n_qb,
                              off=off, has_bias=has_bias, has_segs=has_segs,
                              sq=sq, tail_q=tail_q, sk=sk, tail_k=tail_k),
            grid=(bh, n_kb, n_qb),
            in_specs=[q_spec_t, k_spec_t, k_spec_t, q_spec_t, row_spec_t,
                      row_spec_t]
            + _mask_specs(pl, b, h, sqb, g_map, block_q, block_k,
                          has_bias, has_segs, "kq"),
            out_specs=[k_spec_t, k_spec_t],
            out_shape=[
                jax.ShapeDtypeStruct((bh, sk, d), kt.dtype),
                jax.ShapeDtypeStruct((bh, sk, d), vt.dtype),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=_INTERPRET,
        )(qt, kt, vt, dot, lse, delta, *mask_ins)

        dbias = None
        if has_bias and want_dbias:
            dbias = _dbias_call(pl, pltpu, qt, kt, vt, dot, lse, delta,
                                mask_ins, bias, qseg is not None, b, h, sq,
                                sk, d, block_q, block_k, scale, causal, off)

    return (_unfold(dq, b, h), _unfold(dk, b, h), _unfold(dv, b, h),
            dbias)


def _dbias_call(pl, pltpu, qt, kt, vt, dot, lse, delta, mask_ins, bias,
                has_segs, b, h, sq, sk, d, block_q, block_k, scale, causal,
                off):
    """ds reduced over the bias' broadcast dims.  bh = g·mg + r·mr maps the
    (bias-extent, reduction-extent) grid coordinates back to batch*head."""
    bb, hb, sqb, _ = bias.shape
    sq_full = sqb > 1
    n_qb = sq // block_q
    n_kb = sk // block_k
    if bb == 1 and hb == 1:
        mg, mr, n_r = 0, 1, b * h
    elif bb == 1:
        mg, mr, n_r = 1, h, b          # g = head, reduce over batch
    elif hb == 1:
        mg, mr, n_r = h, 1, h          # g = batch, reduce over heads
    else:
        mg, mr, n_r = 1, 0, 1

    bh_of = lambda g, r: g * mg + r * mr
    dspec = lambda f: pl.BlockSpec((1, block_q, d), f)
    kspec = lambda f: pl.BlockSpec((1, block_k, d), f)
    rspec = lambda f: pl.BlockSpec((1, block_q, 1), f)
    in_specs = [
        dspec(lambda g, kb, qi, r: (bh_of(g, r), qi, 0)),       # q
        kspec(lambda g, kb, qi, r: (bh_of(g, r), kb, 0)),       # k
        kspec(lambda g, kb, qi, r: (bh_of(g, r), kb, 0)),       # v
        dspec(lambda g, kb, qi, r: (bh_of(g, r), qi, 0)),       # do
        rspec(lambda g, kb, qi, r: (bh_of(g, r), qi, 0)),       # lse
        rspec(lambda g, kb, qi, r: (bh_of(g, r), qi, 0)),       # delta
        pl.BlockSpec((1, block_q if sq_full else 1, block_k),
                     lambda g, kb, qi, r: (g, qi if sq_full else 0, kb)),
    ]
    if has_segs:
        in_specs.append(pl.BlockSpec(
            (1, block_q, 1),
            lambda g, kb, qi, r: (bh_of(g, r) // h, qi, 0)))
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k),
            lambda g, kb, qi, r: (bh_of(g, r) // h, 0, kb)))

    bq_b = block_q if sq_full else 1
    out_spec = pl.BlockSpec(
        (1, bq_b, block_k),
        lambda g, kb, qi, r: (g, qi if sq_full else 0, kb))

    db = pl.pallas_call(
        functools.partial(_bwd_dbias_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_qb=n_qb,
                          n_r=n_r, off=off, sq_full=sq_full,
                          has_segs=has_segs),
        grid=(bb * hb, n_kb, n_qb, n_r),
        in_specs=in_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((bb * hb, sqb, sk), bias.dtype),
        scratch_shapes=[pltpu.VMEM((bq_b, block_k), jnp.float32)],
        interpret=_INTERPRET,
    )(qt, kt, vt, dot, lse, delta, *mask_ins)
    return db.reshape(bb, hb, sqb, sk)


def _xla_reference(q, k, v, scale, causal, bias=None, q_seg=None,
                   kv_seg=None):
    qh = jnp.einsum("bshd->bhsd", q)
    kh = jnp.einsum("bshd->bhsd", k)
    vh = jnp.einsum("bshd->bhsd", v)
    s = jnp.einsum("bhsd,bhtd->bhst", qh, kh) * scale
    if bias is not None:
        s = s + bias
    if q_seg is not None:
        seg = q_seg[:, None, :, None] == kv_seg[:, None, None, :]
        s = jnp.where(seg, s, -1e30)
    if causal:
        sq_, sk_ = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq_, sk_), dtype=bool), k=sk_ - sq_)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhst,bhtd->bhsd", p, vh)
    return jnp.einsum("bhsd->bshd", o)


# ---------------------------------------------------------------------------
# custom-vjp wrapper + public dispatcher
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _flash(q, k, v, bias, qseg, kseg, causal, scale):
    out, _ = _flash_fwd(q, k, v, bias, qseg, kseg, scale, causal)
    return out


def _fa_fwd(q, k, v, bias, qseg, kseg, causal, scale):
    # fold ONCE; the folded operands + folded output are the residuals, so
    # the backward kernels read them directly instead of re-deriving the
    # (B*H, S, D) layout from (B,S,H,D) (a measured ~5 ms/step of copies
    # on GPT-2 345M).  The head count is NOT a residual: the backward
    # recovers it statically from the cotangent's (B, Sq, H, D) shape.
    # Memory tradeoff: the folded out_f residual lives alongside the
    # unfolded output until the backward consumes it — one extra
    # activation-sized buffer per attention layer.  Under jax.checkpoint
    # (remat, the near-capacity configuration) residuals are recomputed,
    # not stored, so the cost applies only to no-remat runs with HBM to
    # spare — exactly when the 5 ms matters more than the buffer.
    b, sq, h, d = q.shape
    qt, kt, vt = _fold(q, b, h), _fold(k, b, h), _fold(v, b, h)
    out_f, lse = _flash_fwd_folded(qt, kt, vt, bias, qseg, kseg, scale,
                                   causal, h)
    return _unfold(out_f, b, h), (qt, kt, vt, bias, qseg, kseg, out_f, lse)


def _fa_bwd(causal, scale, res, g):
    qt, kt, vt, bias, qseg, kseg, ot, lse = res
    dq, dk, dv, dbias = _flash_bwd_folded(qt, kt, vt, bias, qseg, kseg,
                                          ot, lse, g, scale, causal,
                                          g.shape[2])
    dseg = None if qseg is None else jnp.zeros_like(qseg)
    dkseg = None if kseg is None else jnp.zeros_like(kseg)
    return (dq, dk, dv, dbias, dseg, dkseg)


_flash.defvjp(_fa_fwd, _fa_bwd)


# bias-nondiff variant: identical forward, but the backward skips the
# dbias reduction kernel entirely.  Under jit the diff'able variant's
# unused dbias would be DCE'd anyway, but the eager tape executes bwd
# rules eagerly — padding masks (never trained) must not pay the extra
# O(S²)-tile sweep there.
@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _flash_nodbias(q, k, v, bias, qseg, kseg, causal, scale):
    out, _ = _flash_fwd(q, k, v, bias, qseg, kseg, scale, causal)
    return out


def _fa_bwd_nodbias(causal, scale, res, g):
    qt, kt, vt, bias, qseg, kseg, ot, lse = res
    dq, dk, dv, _ = _flash_bwd_folded(qt, kt, vt, bias, qseg, kseg, ot,
                                      lse, g, scale, causal, g.shape[2],
                                      want_dbias=False)
    dbias = None if bias is None else jnp.zeros_like(bias)
    dseg = None if qseg is None else jnp.zeros_like(qseg)
    dkseg = None if kseg is None else jnp.zeros_like(kseg)
    return (dq, dk, dv, dbias, dseg, dkseg)


_flash_nodbias.defvjp(_fa_fwd, _fa_bwd_nodbias)


def flash_attention(q, k, v, causal=False, scale=None, bias=None,
                    q_segment_ids=None, kv_segment_ids=None,
                    bias_grad=True):
    """Blockwise attention with optional additive bias / segment masking.

    ``bias``: float additive mask broadcastable to (B, H, Sq, Sk) (each
    leading dim full or 1; key dim full), or a bool mask of the same
    shapes (True = attend).  ``*_segment_ids``: (B, S) int ids; q·k pairs
    with different ids are masked (packed-sequence attention).
    ``bias_grad=False`` promises the bias cotangent is unneeded (padding
    masks): its gradient is returned as zeros and the dbias kernel never
    runs — callers with learned biases (e.g. relative-position) keep the
    default.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, _ = q.shape
    sk = k.shape[1]
    if bias is not None:
        canon = _canon_bias_shape(bias.shape, b, h, sq, sk)
        if canon is None:
            raise ValueError(
                f"flash_attention: bias shape {tuple(bias.shape)} is not "
                f"broadcastable-canonical for q{tuple(q.shape)}/"
                f"k{tuple(k.shape)}")
        if bias.dtype == jnp.bool_:
            bias = jnp.where(bias, 0.0, _NEG_INF).astype(jnp.float32)
        elif bias.dtype != jnp.bfloat16:
            # Mosaic rejects 64-bit inputs (x64 is on framework-wide) and
            # _mask_tile computes in f32 anyway
            bias = bias.astype(jnp.float32)
        bias = bias.reshape(canon)
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("flash_attention: pass both segment-id arrays or "
                         "neither")
    if q_segment_ids is not None:
        # float32 internally: custom_vjp cotangents for int arrays are
        # awkward (float0); exact for ids < 2^24
        q_segment_ids = q_segment_ids.astype(jnp.float32)
        kv_segment_ids = kv_segment_ids.astype(jnp.float32)
    impl = _flash if bias_grad else _flash_nodbias
    return impl(q, k, v, bias, q_segment_ids, kv_segment_ids, bool(causal),
                float(scale))
