"""Fused linear + softmax cross-entropy — Pallas TPU kernels, fwd + bwd.

Plays the reference's fused softmax-CE role
(paddle/fluid/operators/softmax_with_cross_entropy_op.* and the fused-op
tier under operators/fused/) for the LM-head case where it matters: the
(N, V) logits of ``h @ W.T`` are never materialised in HBM.  For GPT-2
(N = B·S = 8192, V = 50257) the baseline path writes and re-reads
~1.7 GB of f32 logits in each direction; here every logits tile lives in
VMEM only, and HBM traffic is O(N·H + V·H) per pass.

Forward: grid (n_blocks, v_blocks), vocab innermost — running (max,
sum-exp) scratch per row block, exactly the flash-attention online
softmax but with no value matrix.  Emits logz (N,) as the residual.
The "gold" logit ``h·W[label]`` is a cheap O(N·H) XLA gather outside.

Backward (p-tiles recomputed from logz, FlashAttention-style):
  - dh:   grid (n_blocks, v_blocks):  dh  += (g·p) @ W,  acc in VMEM.
  - dW:   grid (v_blocks, n_blocks):  dW  += (g·p).T @ h, acc in VMEM.
The label one-hot terms (−g·W[label] into dh, scatter −g·h into dW) are
O(N·H) XLA gathers/scatters outside the kernels.  p is cast to the input
dtype (bf16 on chip) for the second matmul so the MXU runs at full rate;
accumulation stays f32 via preferred_element_type.

Vocab sizes that don't divide the block (50257 = 29·1733 has no useful
factor) ride a padded weight matrix; padded columns are masked to -inf
with an iota guard so the padding never perturbs logsumexp.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Block defaults — sized for ~16 MB VMEM (see module docstring math):
# fwd/dh keep an (bn, H) f32 accumulator, dw a (bv, H) one.
BLOCK_N_FWD = 2048
BLOCK_N_BWD = 1024
BLOCK_V = 512
BLOCK_V_DW = 2048
BLOCK_N_DW = 256
_MIN_BLOCK = 128

# tests flip this to run the kernels in interpreter mode on CPU
_INTERPRET = False


def _backend_is_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def supported(n: int, h: int) -> bool:
    """Can the fused kernel serve this (N tokens, H hidden) head?

    Any token count works: like the vocab axis, a non-divisible N rides
    zero-padded rows (padded loss/grad rows are exactly zero and sliced
    off), and N=0 short-circuits before the kernels.
    """
    if not (_backend_is_tpu() or _INTERPRET):
        return False
    return n >= 0 and h % 128 == 0


def _pick(pref: int, size: int) -> int:
    b = min(pref, size)
    while b > _MIN_BLOCK and size % b:
        b //= 2
    return max(b, _MIN_BLOCK)


from paddle_tpu.ops.pallas.common import dot_nt as _dot_nt, no_x64  # noqa: E402


# ---------------------------------------------------------------------------
# forward: logz = logsumexp_v(h @ W.T)
# ---------------------------------------------------------------------------


def _fwd_kernel(h_ref, w_ref, logz_ref, m_scr, l_scr, *, block_v, n_vb, v):
    from jax.experimental import pallas as pl

    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)

    s = _dot_nt(h_ref[...], w_ref[...])                 # (bn, bv) f32
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < v, s, -jnp.inf)
    m_prev = m_scr[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    alpha = jnp.where(jnp.isfinite(m_prev), alpha, 0.0)
    m_scr[...] = m_new
    l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=1, keepdims=True)

    @pl.when(vb == n_vb - 1)
    def _finish():
        logz_ref[...] = m_scr[...] + jnp.log(jnp.maximum(l_scr[...], 1e-30))


def _ce_logz(h, w_pad, v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, hd = h.shape
    v_pad = w_pad.shape[0]
    block_n = _pick(BLOCK_N_FWD, n)
    block_v = _pick(BLOCK_V, v_pad)
    n_vb = v_pad // block_v

    kernel = functools.partial(_fwd_kernel, block_v=block_v, n_vb=n_vb, v=v)
    with no_x64():
        logz = pl.pallas_call(
            kernel,
            grid=(n // block_n, n_vb),
            in_specs=[
                pl.BlockSpec((block_n, hd), lambda nb, vb: (nb, 0)),
                pl.BlockSpec((block_v, hd), lambda nb, vb: (vb, 0)),
            ],
            out_specs=pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
            out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
            scratch_shapes=[pltpu.VMEM((block_n, 1), jnp.float32),
                            pltpu.VMEM((block_n, 1), jnp.float32)],
            interpret=_INTERPRET,
        )(h, w_pad)
    return logz


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dh_kernel(h_ref, w_ref, logz_ref, g_ref, dh_ref, acc_scr, *, block_v,
               n_vb, v):
    from jax.experimental import pallas as pl

    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    s = _dot_nt(h_ref[...], w_ref[...])                 # (bn, bv) f32
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < v, s, -jnp.inf)
    p = jnp.exp(s - logz_ref[...]) * g_ref[...]         # (bn, bv)
    # cast to the weight dtype so the MXU runs at bf16 rate; f32 acc
    acc_scr[...] += jnp.dot(p.astype(w_ref.dtype), w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(vb == n_vb - 1)
    def _finish():
        dh_ref[...] = acc_scr[...].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, logz_ref, g_ref, dw_ref, acc_scr, *, block_v,
               n_nb, v):
    from jax.experimental import pallas as pl

    vb = pl.program_id(0)
    nb = pl.program_id(1)

    @pl.when(nb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    s = _dot_nt(h_ref[...], w_ref[...])                 # (bn, bv) f32
    col = vb * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < v, s, -jnp.inf)
    p = jnp.exp(s - logz_ref[...]) * g_ref[...]         # (bn, bv)
    # dW_tile += p.T @ h  — contract the token axis
    acc_scr[...] += jax.lax.dot_general(
        p.astype(h_ref.dtype), h_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(nb == n_nb - 1)
    def _finish():
        dw_ref[...] = acc_scr[...].astype(dw_ref.dtype)


def _ce_bwd_kernels(h, w_pad, logz, g, v):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, hd = h.shape
    v_pad = w_pad.shape[0]
    g2 = g.reshape(n, 1).astype(jnp.float32)

    block_n = _pick(BLOCK_N_BWD, n)
    block_v = _pick(BLOCK_V, v_pad)
    with no_x64():
        dh = pl.pallas_call(
            functools.partial(_dh_kernel, block_v=block_v,
                              n_vb=v_pad // block_v, v=v),
            grid=(n // block_n, v_pad // block_v),
            in_specs=[
                pl.BlockSpec((block_n, hd), lambda nb, vb: (nb, 0)),
                pl.BlockSpec((block_v, hd), lambda nb, vb: (vb, 0)),
                pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
                pl.BlockSpec((block_n, 1), lambda nb, vb: (nb, 0)),
            ],
            out_specs=pl.BlockSpec((block_n, hd), lambda nb, vb: (nb, 0)),
            out_shape=jax.ShapeDtypeStruct((n, hd), h.dtype),
            scratch_shapes=[pltpu.VMEM((block_n, hd), jnp.float32)],
            interpret=_INTERPRET,
        )(h, w_pad, logz, g2)

        block_vd = _pick(BLOCK_V_DW, v_pad)
        block_nd = _pick(BLOCK_N_DW, n)
        dw = pl.pallas_call(
            functools.partial(_dw_kernel, block_v=block_vd,
                              n_nb=n // block_nd, v=v),
            grid=(v_pad // block_vd, n // block_nd),
            in_specs=[
                pl.BlockSpec((block_nd, hd), lambda vb, nb: (nb, 0)),
                pl.BlockSpec((block_vd, hd), lambda vb, nb: (vb, 0)),
                pl.BlockSpec((block_nd, 1), lambda vb, nb: (nb, 0)),
                pl.BlockSpec((block_nd, 1), lambda vb, nb: (nb, 0)),
            ],
            out_specs=pl.BlockSpec((block_vd, hd), lambda vb, nb: (vb, 0)),
            out_shape=jax.ShapeDtypeStruct((v_pad, hd), w_pad.dtype),
            scratch_shapes=[pltpu.VMEM((block_vd, hd), jnp.float32)],
            interpret=_INTERPRET,
        )(h, w_pad, logz, g2)
    return dh, dw


# ---------------------------------------------------------------------------
# custom-vjp wrapper + public API
# ---------------------------------------------------------------------------


def _pad_w(w):
    v = w.shape[0]
    v_pad = -(-v // _MIN_BLOCK) * _MIN_BLOCK
    if v_pad != v:
        w = jnp.pad(w, ((0, v_pad - v), (0, 0)))
    return w


def _pad_n(x):
    """Zero-pad the token axis to a _MIN_BLOCK multiple — the grids
    floor n/block, so a remainder would silently drop trailing tokens
    (the PTA601 finding).  Zero rows are exact: the fwd's padded logz
    rows are sliced off, and the bwd pads g with zeros so every padded
    p·g tile is exactly 0 (no dw perturbation)."""
    n = x.shape[0]
    n_pad = max(_MIN_BLOCK, -(-n // _MIN_BLOCK) * _MIN_BLOCK)
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n),) + ((0, 0),) * (x.ndim - 1))
    return x


@jax.custom_vjp
def _fused_ce(h, w, labels_f):
    loss, _ = _fused_ce_fwd(h, w, labels_f)
    return loss


def _fused_ce_fwd(h, w, labels_f):
    v = w.shape[0]
    n = h.shape[0]
    lab = labels_f.astype(jnp.int32)
    if n == 0:
        logz = jnp.zeros((0,), jnp.float32)
        return logz, (h, w, lab, logz)
    w_pad = _pad_w(w)
    logz = _ce_logz(_pad_n(h), w_pad, v)[:n, 0]         # (n,)
    gold_w = jnp.take(w, jnp.clip(lab, 0, v - 1), axis=0)
    gold = jnp.sum(h.astype(jnp.float32) * gold_w.astype(jnp.float32),
                   axis=-1)
    loss = logz - gold                                  # (n,) f32
    return loss, (h, w, lab, logz)


def _fused_ce_bwd(res, g):
    h, w, lab, logz = res
    v, hd = w.shape
    n = h.shape[0]
    if n == 0:
        return (jnp.zeros_like(h), jnp.zeros_like(w),
                jnp.zeros_like(res[2], dtype=jnp.float32))
    w_pad = _pad_w(w)
    # padded rows carry g=0, so their p·g tiles are exactly 0 in both
    # kernels; logz pads with zeros (any finite value works under g=0)
    dh, dw_pad = _ce_bwd_kernels(
        _pad_n(h), w_pad, _pad_n(logz.reshape(n, 1)),
        _pad_n(g.reshape(n, 1)), v)
    dh = dh[:n]
    dw = dw_pad[:v]
    # one-hot (gold) terms, O(N·H) XLA gather/scatter
    gf = g.reshape(n, 1).astype(jnp.float32)
    lab_c = jnp.clip(lab, 0, v - 1)
    dh = dh - (gf * jnp.take(w, lab_c, axis=0).astype(jnp.float32)
               ).astype(dh.dtype)
    # scatter-accumulate in f32: repeated labels (frequent tokens) would
    # round to nothing in a bf16 accumulator
    gold_scatter = jnp.zeros((v, hd), jnp.float32).at[lab_c].add(
        gf * h.astype(jnp.float32))
    dw = (dw.astype(jnp.float32) - gold_scatter).astype(dw.dtype)
    return dh, dw, jnp.zeros_like(res[2], dtype=jnp.float32)


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def fused_linear_cross_entropy(h, w, labels):
    """Per-token ``-log softmax(h @ w.T)[label]`` without materialising
    logits.

    Args:
      h: (N, H) hidden states (any float dtype; bf16 on chip).
      w: (V, H) classifier/embedding weight (tied LM head).
      labels: (N,) integer class ids.  Negative ids are treated as
        padding: their loss entry is computed against class 0 and should
        be masked by the caller (the gradient contribution is whatever
        the caller's mask makes of it — multiply the per-token loss by
        the mask *before* summing).

    Returns (N,) float32 per-token loss.
    """
    # labels ride as f32 (exact for ids < 2^24): custom_vjp wants float
    # cotangents for every positional arg (in-repo precedent:
    # flash_attention segment ids)
    return _fused_ce(h, w, labels.astype(jnp.float32))


def xla_reference(h, w, labels):
    """Unfused reference (materialises logits) for tests/benches."""
    lg = (h @ w.T).astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(lg, axis=-1)
    v = w.shape[0]
    lab = jnp.clip(labels, 0, v - 1)
    gold = jnp.take_along_axis(lg, lab[:, None], axis=-1)[:, 0]
    return logz - gold
