"""Fused Adam/AdamW update — one Pallas pass over (param, grad, m, v).

Reference role: operators/optimizers/adam_op.* and the fused-optimizer
tier (operators/fused/, multi_tensor_adam in later reference versions):
one kernel reads each tensor once and writes p', m', v' — no
intermediate m̂/v̂/update buffers.

On TPU, XLA already fuses the adam expression tree into a small number
of elementwise kernels, so the measured win is modest (see
``tools/op_bench.py --fused-adam`` for the number on the attached
chip); the kernel exists to close the fused-op tier and as the pattern
for update rules XLA fuses badly.

The update rule matches ``optimizer.Adam.update`` exactly (the
``lr_t = lr·√(1−β₂ᵗ)/(1−β₁ᵗ)`` formulation, adam_op.h):

    m' = β₁·m + (1−β₁)·g
    v' = β₂·v + (1−β₂)·g²
    p' = p − lr_t·m'/(√v' + ε) − wd_lr·p     (wd_lr = lr·coeff, AdamW)

Layout: the flat parameter is reshaped to (rows, 128) lanes and tiled
over rows; scalar hyperparameters ride as a (8, 1) block so a changing
learning rate never retraces.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.common import no_x64

BLOCK_ROWS = 1024
_LANES = 128

# tests flip this to run in interpreter mode on CPU
_INTERPRET = False


def _backend_is_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def supported() -> bool:
    return _backend_is_tpu() or _INTERPRET


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, s_ref, po_ref, mo_ref, vo_ref):
    lr_t = s_ref[0, 0]
    beta1 = s_ref[1, 0]
    beta2 = s_ref[2, 0]
    eps = s_ref[3, 0]
    wd_lr = s_ref[4, 0]

    p = p_ref[...]
    g = g_ref[...].astype(jnp.float32)
    m = beta1 * m_ref[...] + (1.0 - beta1) * g
    v = beta2 * v_ref[...] + (1.0 - beta2) * (g * g)
    po_ref[...] = p - lr_t * m / (jnp.sqrt(v) + eps) - wd_lr * p
    mo_ref[...] = m
    vo_ref[...] = v


def fused_adam_update(p, g, m, v, *, lr_t, beta1, beta2, eps, wd_lr=0.0):
    """One fused Adam step on a single tensor.

    ``lr_t`` is the bias-corrected rate (lr·√(1−β₂ᵗ)/(1−β₁ᵗ)); ``wd_lr``
    is the decoupled AdamW decay (lr·coeff), 0 for plain Adam (whose L2
    decay arrives inside ``g`` via the regularizer pipeline).  All
    scalars may be traced — no retrace per step.

    Returns (p', m', v') with the input shapes/dtypes.
    """
    from jax.experimental import pallas as pl

    shape = p.shape
    n = p.size
    rows = -(-n // _LANES)

    block = min(BLOCK_ROWS, rows)
    rows_p = -(-rows // block) * block
    pad = rows_p * _LANES - n

    def flat(x):
        x = x.reshape(-1).astype(jnp.float32)
        if pad:
            x = jnp.pad(x, (0, pad))
        return x.reshape(rows_p, _LANES)

    scalars = jnp.stack([
        jnp.asarray(lr_t, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(wd_lr, jnp.float32),
        jnp.zeros((), jnp.float32),
        jnp.zeros((), jnp.float32),
        jnp.zeros((), jnp.float32),
    ]).reshape(8, 1)

    row_spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    with no_x64():
        po, mo, vo = pl.pallas_call(
            _adam_kernel,
            grid=(rows_p // block,),
            in_specs=[row_spec, row_spec, row_spec, row_spec,
                      pl.BlockSpec((8, 1), lambda i: (0, 0))],
            out_specs=[row_spec, row_spec, row_spec],
            out_shape=[jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32)
                       ] * 3,
            interpret=_INTERPRET,
        )(flat(p), flat(g), flat(m), flat(v), scalars)

    def unflat(x, dtype):
        return x.reshape(-1)[:n].reshape(shape).astype(dtype)

    return (unflat(po, p.dtype), unflat(mo, m.dtype), unflat(vo, v.dtype))


def xla_reference(p, g, m, v, *, lr_t, beta1, beta2, eps, wd_lr=0.0):
    """Unfused reference (the optimizer.Adam.update expression tree)."""
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m2 = beta1 * m + (1 - beta1) * g
    v2 = beta2 * v + (1 - beta2) * g * g
    new_p = pf - lr_t * m2 / (jnp.sqrt(v2) + eps) - wd_lr * pf
    return new_p.astype(p.dtype), m2, v2
