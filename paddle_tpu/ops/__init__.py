"""Low-level op backends: Pallas TPU kernels (ops/pallas) and native C++
host-side engines (ops/native).

Role in the architecture: the TPU-native replacement for the reference's
fused CUDA kernels (paddle/fluid/operators/fused/) and the C++ host runtime
pieces (data feed, embedding tables).
"""
